"""Schedulable inference server — the serving half of the workload story.

The control plane schedules this exactly like the training workload
(BASELINE config shapes: `POST /replicaSet {"cmd": [... serve, ...]}`, port
granted by the port scheduler and passed via --port): it loads a model
(fresh init or an orbax checkpoint produced by workloads/train_llama.py,
including grouped-layout checkpoints from interleaved-pipelined runs), and
answers token-level generation requests over HTTP.

Token-level by design: the reference schedules opaque containers and speaks
no NLP; this framework is tokenizer-agnostic the same way — bring your own
tokenizer, send token ids.

API (same envelope as the control plane):
  GET  /healthz               -> {"code":200, "data":{"model","params", ...}}
  POST /generate              body {"tokens": [[...]], "max_new": N,
                                    "temperature": 0.0, "top_k": 0,
                                    "top_p": 1.0}
                              -> {"code":200, "data":{"tokens": [[...]]}}

Serving is single-flight (one chip, one compiled program at a time); each
new (batch, prompt_len, max_new, temperature) shape pays one XLA compile
(amortized by the shared JAX_COMPILATION_CACHE_DIR the control plane
injects), then streams from the compiled KV-cache decode loop (infer.py).
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def _load_params(trainer, ckpt_dir: str | None, init_key: int = 0):
    import jax

    if not ckpt_dir:
        return trainer.init(jax.random.key(init_key))["params"]
    from ..train import restore_checkpoint
    # orbax needs an absolute path; scheduled workloads pass volume-bind
    # paths relative to $CONTAINER_ROOT (the process substrate's cwd)
    state, step = restore_checkpoint(os.path.abspath(ckpt_dir))
    print(f"restored checkpoint step {step}", flush=True)
    return state["params"]


def _maybe_ungroup(params: dict, config) -> dict:
    """Checkpoints from interleaved-pipelined trainers store layers as
    [v, pp, Lc, ...] (pipeline.group_layers). The sequential KV-cache
    forward needs the canonical [L, ...] stack; detect the two extra
    leading dims against the family's canonical shapes and ungroup."""
    import jax

    from ..models import family_for
    from ..parallel.pipeline import ungroup_layers

    canonical = jax.eval_shape(
        lambda: family_for(config).init_params(config, jax.random.key(0)))
    got = jax.tree.leaves(params["layers"])[0].ndim
    want = jax.tree.leaves(canonical["layers"])[0].ndim
    if got == want:
        return params
    if got == want + 2:
        lead = jax.tree.leaves(params["layers"])[0].shape
        v, pp = int(lead[0]), int(lead[1])
        params = dict(params)
        params["layers"] = ungroup_layers(params["layers"], pp, v)
        print(f"ungrouped interleaved checkpoint (v={v}, pp={pp})",
              flush=True)
        return params
    raise ValueError(
        f"layer leaves have {got} dims, expected {want} (canonical) or "
        f"{want + 2} (group_layers layout)")


class _Batcher:
    """Continuous batching (batching.py): one background thread owns a
    slot cache; greedy requests enqueue, claim a free slot, prefill, and
    then every decode step advances ALL active slots together — a new
    request joins between steps instead of waiting for the batch to
    drain. Decode is weight-bound, so occupied slots are nearly free
    throughput."""

    def __init__(self, config, params, slots: int, max_len: int,
                 prefill_chunk: int = 0, prefix_cache: int = 0,
                 restarts: int = 3, kv_quant: bool = False,
                 kv_block: int = 0, kv_pool_blocks: int = 0,
                 decode_chunk: int = 1, seed: int | None = None,
                 draft: tuple | None = None, gamma: int = 4,
                 regulator=None):
        import collections
        import queue

        self.config = config
        self.params = params
        self.max_len = max_len
        # multi-tenant chip sharing: a regulator.Tenant handle gates every
        # device chunk this batcher issues (admission by share weight;
        # latency-class co-tenants preempt at the chunk boundary). None =
        # dedicated chip, zero added cost.
        self._regulator = regulator
        # speculative decoding INSIDE the batch: a draft model (own slot
        # cache) proposes gamma tokens per active row each round; the
        # target verifies every row's gamma+1 positions in ONE multi-token
        # forward (slot_verify); acceptance/rollback is per row. Greedy
        # rows emit exactly the target-only greedy stream; sampling rows
        # keep exact target statistics (rowwise_spec_accept). The slot
        # caches get gamma+1 positions of headroom: the verify step may
        # overshoot a row's budget before its rollback.
        self._draft = draft                  # (draft_config, draft_params)
        self.gamma = int(gamma)
        if draft is not None and draft[0].vocab_size != config.vocab_size:
            raise ValueError("draft and target must share a vocab")
        self._cache_len = max_len + (self.gamma + 1 if draft else 0)
        # paged x speculative: the verify step writes gamma+1 tokens
        # starting AT a row's frontier before its rollback, and a row's
        # frontier tops out at prompt+max_new-2 (the arm token is never
        # cache-resident when its round runs) — so written positions
        # top out at prompt+max_new+gamma-2, inside a reservation of
        # prompt+max_new+gamma positions (one spare, matching the dense
        # path's gamma+1 convention). Admission reserves that budget
        # (spec_pad extra tokens) UP FRONT: rollback stays
        # pure length arithmetic (the over-written blocks are the row's
        # own, reserved, and simply re-written by the next round), no
        # mid-stream block alloc can deadlock, and no active row's
        # verify write ever falls through the page table to the shared
        # scratch block (where concurrent rows' overshoots would corrupt
        # each other's verify logits).
        self._spec_pad = self.gamma if draft else 0
        self.spec_rounds = 0                 # spec telemetry (healthz/bench)
        self.spec_proposed = 0               # draft tokens proposed
        self.spec_accepted = 0               # draft tokens accepted
        self.spec_emitted = 0                # tokens emitted by spec rounds
        # > 1: when nothing is waiting to join, decode up to this many
        # steps as ONE device-side scan per host sync — the per-step
        # argmax fetch is pure dispatch/RTT overhead (VERDICT r2 weak
        # #6); chunking amortizes it. Waiting work drops the loop back
        # to single steps so admission latency stays one step.
        self.decode_chunk = max(int(decode_chunk), 1)
        # PRNG for per-request sampling rows (rowwise_pick: temp 0 rows
        # stay exactly greedy); one base key folded by a step counter so
        # every decode step / admission pick gets a fresh subkey. A fixed
        # seed makes a batcher's sampled streams reproducible (tests).
        self._seed = (seed if seed is not None
                      else int.from_bytes(os.urandom(4), "big"))
        self._step_counter = 0
        # int8 slot cache: half the decode-loop HBM reads (same numerics
        # as infer.py's kv_quant path — per-token-per-head scales)
        self.kv_quant = kv_quant
        # kv_block > 0: PAGED cache (paging.py) — slots share a pool of
        # kv_pool_blocks blocks of kv_block tokens instead of dense
        # slots x max_len reservations; admission waits on free blocks.
        # Default pool = full capacity (operators shrink it to cap HBM).
        self._paged = kv_block > 0
        self.kv_block = kv_block
        if self._paged:
            self._max_pages = -(-(max_len + self._spec_pad) // kv_block)
            self.kv_pool_blocks = (kv_pool_blocks
                                   or 1 + slots * self._max_pages)
        else:
            self.kv_pool_blocks = 0
        # scheduler crash budget: a transient device/XLA error fails the
        # in-flight requests but the loop re-initializes its cache and
        # keeps serving; after `restarts` crashes the batcher stays dead
        # (a persistent fault must not retry forever)
        self._restarts_left = restarts
        self._prefill_cursor = 0
        # > 0: feed prompts to the model in pieces of this many tokens,
        # one piece per loop tick, so a long prefill interleaves with
        # decode steps for the other slots instead of stalling them
        self.prefill_chunk = prefill_chunk
        # > 0: keep the KV of the last N distinct prompts; a new request
        # whose prompt extends a stored one restores that prefix's KV and
        # prefills only the suffix (system-prompt reuse). LRU by prompt.
        self.prefix_cache = prefix_cache
        self._prefixes: "collections.OrderedDict" = collections.OrderedDict()
        self.prefix_hits = 0
        self.queue: "queue.Queue" = queue.Queue()
        # queue-wait telemetry (submit -> slot admission): per-request
        # value rides the item dict (stats_out) and the response header;
        # these aggregates feed /healthz batching.queueWait
        self.queue_wait_count = 0
        self.queue_wait_ms_total = 0.0
        self.last_queue_wait_ms: "float | None" = None
        # EWMA twin of last_queue_wait_ms: the affinity router scores on
        # this (X-TDAPI-Queue-Wait-EWMA-Ms / healthz ewmaMs) — a point
        # sample is too noisy under bursts; the old field stays for
        # compat. Alpha 0.2 ~= a 5-request memory.
        self.queue_wait_ewma_ms: "float | None" = None
        # KV handoff (prefill/decode disaggregation): prompt-KV exports
        # parked for a decode replica's GET /kv, TTL-purged by the
        # scheduler so a crashed/vanished decode peer can never leak
        # pool blocks (the kill-mid-handoff sweep invariant)
        self._kv_export_ttl = float(
            os.environ.get("TDAPI_KV_EXPORT_TTL_S", "30"))
        self.kv_handoffs_in = 0              # imports spliced (decode side)
        self.prefix_evictions = 0            # trie leaves dropped (pressure)
        self.slots: list = [None] * slots
        self._waiting = None      # paged: head-of-line item short on blocks
        self._sample_vec = None   # per-slot sampling vectors (cached)
        self._make_cache()
        self._stop = False
        self._dead: Exception | None = None   # loop crash / close reason
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _build(self, init_fn, kv_sharded: bool = False):
        """Materialize one freshly-initialized cache pytree. Hook: the
        lock-step subclass jits init_fn with mesh out_shardings so the
        arrays are GLOBAL over its mesh (the jitted slot-ops mix the
        cache with mesh-sharded params); kv_sharded marks the TARGET
        cache, whose K/V buffers it may additionally shard over tp."""
        return init_fn()

    def _make_cache(self) -> None:
        """(Re)build the device cache + host allocator state — init and
        the crash-restart path share it."""
        if self._paged:
            from ..paging import BlockAllocator, init_paged_cache
            self.cache = self._build(lambda: init_paged_cache(
                self.config, self.kv_pool_blocks, self.kv_block,
                len(self.slots), self._max_pages, quantized=self.kv_quant),
                kv_sharded=True)
            self._alloc = BlockAllocator(self.kv_pool_blocks)
            self._slot_blocks: list = [None] * len(self.slots)
            # paged prefix store is a TRIE over block-sized token chunks
            # (shared-prefix prompts share nodes AND physical blocks);
            # rebuilt with the allocator on crash-restart so the two can
            # never disagree about which blocks are live
            from ..batching import PrefixTrie
            self._trie = (PrefixTrie(self.kv_block)
                          if self.prefix_cache else None)
            self._kv_exports: dict = {}
            # (sketch hex, occupied blocks, indexed prefixes) — refreshed
            # by the scheduler thread when the trie changes; the HTTP
            # thread only ever reads the tuple (atomic reassignment)
            from .. import kvaffinity
            self._sketch_pub = (
                kvaffinity.encode_sketch_hex([0] * kvaffinity.SKETCH_WORDS),
                0, 0)
            self._sketch_dirty = False
        else:
            self._trie = None
            self._kv_exports = {}
            from ..batching import init_slot_cache
            self.cache = self._build(lambda: init_slot_cache(
                self.config, len(self.slots), self._cache_len,
                quantized=self.kv_quant), kv_sharded=True)
        if self._draft is not None:
            from ..batching import init_slot_cache
            self.d_cache = self._build(lambda: init_slot_cache(
                self._draft[0], len(self.slots), self._cache_len,
                quantized=self.kv_quant))

    # the cache entry points, dispatched on dense vs paged mode (the
    # import + attribute lookup per call is trivia next to the jitted
    # call itself; _loop hoists decode only because it's per-token-hot)
    def _fn_prefill(self):
        if self._paged:
            from ..paging import paged_prefill
            return paged_prefill
        from ..batching import slot_prefill
        return slot_prefill

    def _fn_decode(self):
        if self._paged:
            from ..paging import paged_decode
            return paged_decode
        from ..batching import slot_decode
        return slot_decode

    def _fn_decode_pick(self):
        if self._paged:
            from ..paging import paged_decode_pick
            return paged_decode_pick
        from ..batching import slot_decode_pick
        return slot_decode_pick

    def _fn_decode_multi(self):
        if self._paged:
            from ..paging import paged_decode_multi
            return paged_decode_multi
        from ..batching import slot_decode_multi
        return slot_decode_multi

    def _fn_verify(self):
        """TARGET-side speculative verify (the draft always runs a dense
        slot cache: it is the small model — paging the TARGET's KV is
        the HBM win, and one allocator per batcher keeps admission
        single-source-of-truth)."""
        if self._paged:
            from ..paging import paged_verify
            return paged_verify
        from ..batching import slot_verify
        return slot_verify

    def _release_slot(self, i: int) -> None:
        """Free a slot AND (paged) return its blocks to the pool."""
        self.slots[i] = None
        self._sample_vec = None
        if self._paged and self._slot_blocks[i]:
            self._alloc.free(self._slot_blocks[i])
            self._slot_blocks[i] = None

    def submit(self, prompt_row, max_new: int, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 1.0,
               stats_out: dict | None = None, kv_key: str = "",
               kv_import: dict | None = None) -> list[int]:
        """Blocking: returns the stream for one sequence — greedy at
        temperature 0, else per-request sampling (the row picks its token
        via rowwise_pick inside the shared decode step; other rows'
        streams are untouched). Raises if the scheduler thread has died
        or the batcher is closed — a request must never hang on an event
        nobody will set. `stats_out` (a dict) receives per-request
        telemetry — queueWaitMs, the submit->slot-admission wait — for
        the HTTP layer's response headers."""
        if self._stop or self._dead is not None:
            raise RuntimeError(
                f"batcher unavailable: {self._dead or 'closed'}")
        if prompt_row.shape[0] == 0:
            # chunked admission would park an empty chunks list forever;
            # the plain path would crash the scheduler — reject up front
            raise ValueError("empty prompt")
        import math

        import numpy as np
        # validate the F32-ROUNDED values — the sampling vectors (and the
        # lock-step broadcast wire) are float32, so a subnormal f64 that
        # passes an f64 range check but rounds to 0.0f would empty the
        # nucleus downstream: the silent degradation this validation
        # exists to reject. (temperature rounding to 0.0f is safe — that
        # IS the greedy gate value on every path.)
        temperature = float(np.float32(temperature))
        top_p = float(np.float32(top_p))
        if not (math.isfinite(temperature) and temperature >= 0):
            # NaN slips through a bare `< 0` check (json accepts the NaN
            # literal) and would silently stream garbage
            raise ValueError("temperature must be finite and >= 0")
        if not 0.0 < top_p <= 1.0:
            # top_p <= 0 would empty the nucleus and silently degrade to
            # a stream of token 0 — fail loudly instead
            raise ValueError("top_p must be in (0, 1]")
        if top_k < 0:
            raise ValueError("top_k must be >= 0")
        # top_k >= vocab means "no filter" (the kth-largest cutoff is the
        # minimum) — clamp so the int32 sampling vectors / broadcast wire
        # can't overflow on a huge-but-semantically-valid value
        top_k = min(int(top_k), self.config.vocab_size)
        if prompt_row.shape[0] + max_new > self.max_len:
            raise ValueError(
                f"prompt {prompt_row.shape[0]} + max_new {max_new} exceeds "
                f"the batcher's max_len {self.max_len}")
        if self._paged:
            needed = -(-(prompt_row.shape[0] + max_new + self._spec_pad)
                       // self.kv_block)
            if needed > self.kv_pool_blocks - 1:    # block 0 is scratch
                raise ValueError(
                    f"request needs {needed} KV blocks but the pool only "
                    f"has {self.kv_pool_blocks - 1} — it could never be "
                    f"admitted")
        item = {"prompt": prompt_row, "max_new": int(max_new),
                "temperature": float(temperature), "top_k": int(top_k),
                "top_p": float(top_p),
                # queue-wait clock: _admit stamps wait_ms when the item
                # lands in a slot; the HTTP layer advertises it per
                # response (X-TDAPI-Queue-Wait-Ms) so a fronting worker's
                # trace can stitch replica-side time in
                "enq_at": time.monotonic(),
                "done": threading.Event(), "out": None, "error": None}
        # disaggregated handoff riders (paged mode only): a prefill-phase
        # request exports its prompt KV under kv_key; a decode-phase
        # request splices a fetched export in instead of re-prefilling
        if kv_key and self._paged:
            item["_kv_key"] = kv_key
        if kv_import is not None and self._paged:
            item["_kv_import"] = kv_import
        self.queue.put(item)
        # re-check AFTER the put: _fail_all may have drained the queue
        # between our _dead check and the put, leaving this item in a dead
        # queue that nobody will ever service
        if ((self._stop or self._dead is not None)
                and not item["done"].is_set()):
            item["error"] = self._dead or RuntimeError("batcher closed")
            item["done"].set()
        item["done"].wait()
        if item["error"] is not None:
            raise RuntimeError(f"batcher failed: {item['error']}")
        if stats_out is not None and "wait_ms" in item:
            stats_out["queueWaitMs"] = round(item["wait_ms"], 3)
        return item["out"]

    @property
    def alive(self) -> bool:
        """Scheduler thread is running and accepting work (/healthz)."""
        return self._dead is None and not self._stop

    @property
    def queued(self) -> int:
        """Requests waiting for a slot (/healthz); the lock-step
        subclass adds its broadcast-synced pending list."""
        return self.queue.qsize() + (self._waiting is not None)

    def close(self):
        self._stop = True
        self.thread.join(timeout=5)
        self._fail_all(RuntimeError("batcher closed"))

    def _fail_all(self, exc: Exception) -> None:
        """Release every waiter — in-flight slots, the parked head-of-line
        item, and queued items; the scheduler is gone, so blocking forever
        is the only alternative."""
        import queue
        self._dead = self._dead or exc
        for i, s in enumerate(self.slots):
            if s is not None:
                s["error"] = exc
                s["done"].set()
                self._release_slot(i)
        if self._waiting is not None:
            self._waiting["error"] = exc
            self._waiting["done"].set()
            self._waiting = None
        while True:
            try:
                item = self.queue.get_nowait()
            except queue.Empty:
                break
            item["error"] = exc
            item["done"].set()

    def _run(self):
        while True:
            try:
                self._loop()
                return
            except Exception as e:  # noqa: BLE001 — device OOM/XLA errors
                # land here; every waiter must be released, not left hanging
                import traceback
                traceback.print_exc()
                self._fail_all(e)
                if self._stop or self._restarts_left <= 0:
                    return
                # one transient device error must not disable continuous
                # batching for the process lifetime: the crash failed every
                # in-flight waiter above, so the cache holds only dead
                # rows — rebuild it and resume accepting work
                self._restarts_left -= 1
                self._make_cache()
                self._prefixes.clear()
                if self._stop:
                    # close() ran while we rebuilt (its join can time out
                    # mid-rebuild): clearing _dead now would make a batcher
                    # that is about to exit report alive
                    return
                self._dead = None
                print(f"batcher scheduler restarted after: {e!r} "
                      f"({self._restarts_left} restarts left)", flush=True)

    # ---- the scheduler loop (single thread owns the cache) ----

    def _next_item(self):
        """FIFO head: the parked head-of-line item (paged admission short
        on blocks) before anything newly queued. None = nothing waiting."""
        import queue
        if self._waiting is not None:
            item, self._waiting = self._waiting, None
            return item
        try:
            return self.queue.get_nowait()
        except queue.Empty:
            return None

    def _admit(self):
        """Claim free slots for queued items. Without chunking, the whole
        prompt prefills here; with chunking, the item parks in the slot
        with its remaining pieces and _prefill_tick feeds them. Paged
        mode additionally reserves the request's blocks from the shared
        pool — short on blocks, the item waits at the head of the line
        (FIFO: later small requests must not starve it)."""
        import jax.numpy as jnp

        for i, s in enumerate(self.slots):
            if s is not None:
                continue
            item = self._next_item()
            if item is None:
                return
            shared_tok, donor = 0, None
            if self._paged:
                prompt_len = item["prompt"].shape[0]
                # ZERO-COPY prefix reuse: a cached prompt prefix's FULL
                # blocks go straight into this slot's page table (rc++).
                # Writes can never touch them — the first private
                # position starts the first private block — so no copy
                # and no copy-on-write are ever needed.
                shared, shared_tok, donor = self._paged_prefix_lookup(item)
                if shared:
                    # take OUR reference first: any eviction below (even
                    # of the entry we share from) then can't return these
                    # blocks to the free list under us
                    self._alloc.share(shared)
                total = -(-(prompt_len + item["max_new"] + self._spec_pad)
                          // self.kv_block)
                blocks = self._alloc.alloc(total - len(shared))
                # pool pressure: stored prefixes are a CACHE, not a
                # reservation — evict LRU entries until the request fits
                # (their blocks free once nothing else references them).
                # Without this a parked request could deadlock behind
                # pinned prefixes that only admissions would ever evict.
                while blocks is None and self._evict_prefix():
                    blocks = self._alloc.alloc(total - len(shared))
                if blocks is None:
                    if shared:
                        self._alloc.free(shared)    # release our claim
                    item.pop("_key", None)
                    # not enough pool: park and retry when slots finish
                    self._waiting = item
                    return
                if shared:
                    self.prefix_hits += 1
                    item["_restored"] = True
                row_blocks = shared + blocks
                self._slot_blocks[i] = row_blocks
                row = [0] * self._max_pages
                row[:len(row_blocks)] = row_blocks
                self.cache["pages"] = self.cache["pages"].at[i].set(
                    jnp.array(row, jnp.int32))
                # disaggregated handoff, decode side: splice the prefill
                # replica's exported prompt KV into this slot's private
                # blocks and skip re-prefilling those tokens. Mutually
                # exclusive with local prefix sharing — a local hit is
                # already zero-copy and strictly better.
                imp = item.pop("_kv_import", None)
                if imp is not None and not shared_tok:
                    shared_tok = self._kv_inject(i, row_blocks, imp, item)
                    if shared_tok:
                        item["_restored"] = True
                        self.kv_handoffs_in += 1
                if shared_tok:
                    self.cache["lengths"] = self.cache["lengths"].at[
                        i].set(shared_tok)
            # admission is the queue-wait boundary: stamp once (a paged
            # park re-offers the same item later — its wait keeps
            # accruing until the admission that sticks). Lock-step
            # non-zero ranks see broadcast-built items without the
            # clock; only rank 0 (the one with real HTTP waiters)
            # records.
            if "wait_ms" not in item and "enq_at" in item:
                item["wait_ms"] = (time.monotonic()
                                   - item["enq_at"]) * 1e3
                self.queue_wait_count += 1
                self.queue_wait_ms_total += item["wait_ms"]
                self.last_queue_wait_ms = item["wait_ms"]
                prev = self.queue_wait_ewma_ms
                self.queue_wait_ewma_ms = (
                    item["wait_ms"] if prev is None
                    else 0.2 * item["wait_ms"] + 0.8 * prev)
            try:
                rem = (item["prompt"][shared_tok:] if self._paged
                       else self._restore_prefix(i, item))
                # an in-flight donor still mid-prefill hasn't written the
                # shared positions yet: park the suffix (even unchunked)
                # and let _prefill_tick start it once the donor's write
                # frontier passes shared_tok. _written stays 0 until then
                # so a third request sharing from THIS item waits too.
                awaiting = (self._paged and donor is not None)
                if awaiting:
                    item["_await"] = (donor, shared_tok)
                else:
                    item["_written"] = shared_tok
                if self.prefill_chunk > 0 or awaiting:
                    c = self.prefill_chunk or rem.shape[0]
                    item["chunks"] = [rem[j:j + c]
                                      for j in range(0, rem.shape[0], c)]
                    if self._draft is not None:
                        # the draft sees the FULL prompt (no stored draft
                        # prefixes), chunked the same way
                        item["dchunks"] = [
                            item["prompt"][j:j + c]
                            for j in range(0, item["prompt"].shape[0], c)]
                    item["stream"] = None        # not decodable yet
                    self.slots[i] = item
                    self._sample_vec = None
                else:
                    self._prefill_piece(i, item, rem,
                                        first=not item.get("_restored"))
                    if self._draft is not None:
                        # full prompt even when the target restored a
                        # prefix: only the target has a prefix store
                        self._draft_prefill(i, item["prompt"], first=True)
                    self._arm_or_finish(i, item)
            except Exception as e:
                # the item is in neither the queue nor a slot here — fail
                # it directly, then let the crash propagate (_run releases
                # everyone else)
                item["error"] = e
                item["done"].set()
                raise

    # ---- prefix cache (system-prompt KV reuse) ----

    @staticmethod
    def _prompt_key(item) -> tuple:
        """Host prompt tuple, cached on the item (ONE device-to-host
        transfer per request, shared by every lookup that needs it)."""
        import jax
        key = item.get("_key") or tuple(
            jax.device_get(item["prompt"]).tolist())
        item["_key"] = key
        return key

    @staticmethod
    def _usable_lcp(a: tuple, b: tuple) -> int:
        """Longest common prefix usable for KV reuse when serving prompt
        `b` — capped at len(b)-1 so the last position's logits always
        come from a real forward."""
        lcp = 0
        for x, y in zip(a, b):
            if x != y:
                break
            lcp += 1
        return min(lcp, len(b) - 1)

    def _lcp_lookup(self, item):
        """(best stored key, usable token count) for the item's prompt."""
        key = self._prompt_key(item)
        best_key, best_use = None, 0
        for pk in self._prefixes:
            usable = self._usable_lcp(pk, key)
            if usable > best_use:
                best_key, best_use = pk, usable
        return best_key, best_use

    def _restore_prefix(self, i, item):
        """Dense mode: longest stored prompt prefix -> COPY its KV into
        the slot row, return only the tokens still needing prefill."""
        prompt = item["prompt"]
        if not self.prefix_cache:
            return prompt
        import jax.numpy as jnp

        from ..batching import slot_restore_kv
        best_key, best_use = self._lcp_lookup(item)
        if best_key is None or best_use < 8:     # not worth a restore
            return prompt
        entry = self._prefixes[best_key]
        self._prefixes.move_to_end(best_key)
        self.cache = slot_restore_kv(self.cache, jnp.int32(i),
                                     entry["bufs"], best_use)
        self.prefix_hits += 1
        item["_restored"] = True
        return prompt[best_use:]

    def _paged_prefix_lookup(self, item):
        """Paged mode: (shared block list, shared token count, donor item
        or None). Two sources, best (longest) wins:

        - the prefix STORE (completed prompts kept by --prefix-cache):
          the stored prefix's FULL blocks go straight into the new slot's
          page table (rc++), no data movement, no waiting;
        - IN-FLIGHT slots (always on in paged mode): a running/mid-
          prefill request whose prompt shares a block-aligned prefix
          donates its prefix blocks the same zero-copy way — N identical
          prompts arriving in one burst allocate ~one prompt's blocks
          (VERDICT r3 next #5). A donor still mid-prefill hasn't written
          the shared positions yet, so the follower is returned WITH the
          donor item and parks until the donor's write frontier
          (_written) passes the shared token count — acyclic by
          construction (a follower only awaits an earlier admission).

        Sharing is safe because shared blocks are never written again:
        the donor's decode writes start at its prompt length (>= the
        shared tokens, which are FULL prompt blocks), and the follower's
        prefill starts at shared_tok — both inside private blocks."""
        best_blocks, best_tok, best_donor = [], 0, None
        if self._trie is not None:
            key = self._prompt_key(item)
            blocks, _ = self._trie.lookup(key)
            # cap at len-1 blocks' worth: the last position's logits must
            # come from a real forward (same rule as _usable_lcp)
            n_blk = min(len(blocks), (len(key) - 1) // self.kv_block)
            if n_blk >= 1:
                best_blocks = blocks[:n_blk]
                best_tok = n_blk * self.kv_block
        # in-flight donors: any occupied slot with a longer common prefix
        key = self._prompt_key(item)
        for j, sj in enumerate(self.slots):
            if sj is None or self._slot_blocks[j] is None:
                continue
            usable = self._usable_lcp(self._prompt_key(sj), key)
            n_blk = min(usable // self.kv_block,
                        len(self._slot_blocks[j]))
            if n_blk * self.kv_block > best_tok:
                best_blocks = self._slot_blocks[j][:n_blk]
                best_tok = n_blk * self.kv_block
                # no wait needed once the donor's writes cover the prefix
                best_donor = (sj if sj.get("_written", 0) < best_tok
                              else None)
        return best_blocks, best_tok, best_donor

    def _store_prefix(self, i, item) -> None:
        """After a full prefill, keep the prompt's KV for future requests
        sharing the prefix (LRU-bounded). Dense mode copies the rows out
        (bucketed to 64 so the extract jit variety stays small); paged
        mode just rc++'s the prompt's FULL blocks — zero copy (those
        blocks are never written again: decode writes start at
        prompt_len, inside the first private block)."""
        if not self.prefix_cache:
            return
        import jax
        import jax.numpy as jnp

        key = item.get("_key") or tuple(
            jax.device_get(item["prompt"]).tolist())
        if self._paged:
            # trie-indexed donation: the prompt's FULL blocks join the
            # prefix trie (levels already indexed by an earlier prompt
            # keep their existing blocks — insert reports only the new
            # ones, and only those get the extra reference). No count
            # bound: entries are LRU-evicted ONLY under pool pressure
            # (_evict_prefix), so a quiet pool keeps everything warm.
            if self._trie is None:
                return
            n_store = len(key) // self.kv_block
            if n_store < 1:
                return
            new = self._trie.insert(key, self._slot_blocks[i][:n_store])
            if new:
                self._alloc.share(new)           # survive the slot release
                self._sketch_dirty = True
            return
        if key in self._prefixes:
            self._prefixes.move_to_end(key)
            return
        from ..batching import slot_extract_kv
        if len(key) < 8:
            # below the restore threshold: an entry this short can never
            # be restored — storing it would only evict useful prefixes
            return
        # ceil-to-64 never exceeds max_len here: submit() enforces
        # len + max_new <= max_len with max_new >= 1
        bucket = min(self.max_len, -(-len(key) // 64) * 64)
        bufs = slot_extract_kv(self.cache, jnp.int32(i), bucket)
        self._prefixes[key] = {"bufs": bufs}
        while len(self._prefixes) > self.prefix_cache:
            self._prefixes.popitem(last=False)

    def _evict_prefix(self) -> bool:
        """Drop ONE stored prefix under pool pressure (paged: the trie's
        LRU leaf — interior blocks back every prefix through them, so
        leaf-first is the only safe order). True when something freed."""
        if self._trie is not None:
            freed = self._trie.evict_lru()
            if not freed:
                return False
            self._alloc.free(freed)
            self.prefix_evictions += 1
            self._sketch_dirty = True
            return True
        if self._prefixes:
            _, ev = self._prefixes.popitem(last=False)
            self._alloc.free(ev["blocks"])
            self.prefix_evictions += 1
            return True
        return False

    # ---- KV handoff (prefill/decode disaggregation) ----

    def _kv_export(self, i, item) -> None:
        """Prefill phase done: checkpoint the prompt's KV so a decode
        replica can fetch it via GET /kv. The device gather runs HERE —
        the scheduler thread is the cache's only owner; the HTTP thread
        serves the finished host copy. The prompt blocks are ALSO rc++'d
        into the export entry: a same-replica decode still reuses them
        zero-copy through the trie, and the TTL purge (not the fetch
        peer's goodwill) frees them — the kill-mid-handoff sweep pins
        that no crash between phases can leak pool blocks."""
        from ..paging import paged_extract_blocks
        key = self._prompt_key(item)
        plen = len(key)
        n_blk = -(-plen // self.kv_block)
        blocks = self._slot_blocks[i][:n_blk]
        self._alloc.share(blocks)
        self._kv_exports[item["_kv_key"]] = {
            "tokens": key, "len": plen, "blocks": blocks,
            "bufs": paged_extract_blocks(self.cache, blocks),
            "at": time.monotonic()}

    def _kv_inject(self, i, row_blocks, imp, item) -> int:
        """Splice a fetched export into this slot's private blocks;
        returns resident token count (0 = mismatch, prefill instead).
        The import may end in a PARTIAL block — fine: the suffix prefill
        appends into that block's remaining positions, and every touched
        block is this slot's own."""
        from ..paging import paged_inject_blocks
        key = self._prompt_key(item)
        toks = tuple(imp.get("tokens") or ())
        # the export must be a strict prefix: >= 1 suffix token keeps the
        # first decode logits coming from a real forward
        if not toks or len(toks) >= len(key) or key[:len(toks)] != toks:
            return 0
        n_blk = -(-len(toks) // self.kv_block)
        if n_blk > len(row_blocks):
            return 0
        try:
            self.cache = paged_inject_blocks(
                self.cache, row_blocks[:n_blk], imp["bufs"])
        except (KeyError, ValueError, TypeError):
            return 0                 # malformed fetch -> full prefill
        return len(toks)

    def kv_take(self, key: str):
        """HTTP thread: claim an export's host KV (once). Block frees
        stay on the scheduler thread (_purge_kv_exports) — the allocator
        has exactly one owner."""
        if not key:
            return None
        e = self._kv_exports.get(key)
        if e is None or e.get("taken"):
            return None
        e["taken"] = True
        return e

    def _purge_kv_exports(self) -> None:
        """Scheduler tick: free taken/expired exports' block refs."""
        if not self._kv_exports:
            return
        now = time.monotonic()
        for k, e in list(self._kv_exports.items()):
            if e.get("taken") or now - e["at"] > self._kv_export_ttl:
                self._kv_exports.pop(k, None)
                self._alloc.free(e["blocks"])

    def _refresh_sketch(self) -> None:
        """Rebuild the advertised prefix sketch from the trie (scheduler
        thread; the HTTP thread reads the published tuple). Hashing a
        leaf's full path covers all ancestor levels, so leaves suffice."""
        from .. import kvaffinity
        hashes: list = []
        for prefix in self._trie.iter_leaf_prefixes():
            hashes.extend(kvaffinity.chunk_hashes(prefix))
        self._sketch_pub = (
            kvaffinity.encode_sketch_hex(kvaffinity.build_sketch(hashes)),
            len(self._trie), self._trie.leaf_count)
        self._sketch_dirty = False

    def _prefill_piece(self, i, item, piece, first: bool):
        import jax
        import jax.numpy as jnp

        logits, self.cache = self._fn_prefill()(
            self.params, piece[None], self.cache, jnp.int32(i),
            self.config, append=not first)
        item["_last_logits"] = logits
        # host-side write frontier: how many of this item's prompt tokens
        # are IN the cache — in-flight paged prefix sharing gates a
        # follower's prefill on its donor's frontier
        item["_written"] = item.get("_written", 0) + int(piece.shape[0])

    def _draft_prefill(self, i, piece, first: bool):
        """Feed a prompt piece into the DRAFT's slot cache (speculative
        mode keeps the two caches in lock-step: both hold y_1..y_{m-1}
        between rounds). The draft's logits are unused at prefill — its
        first proposal comes off the first spec round."""
        import jax.numpy as jnp

        from ..batching import slot_prefill
        dcfg, dparams = self._draft
        _, self.d_cache = slot_prefill(dparams, piece[None], self.d_cache,
                                       jnp.int32(i), dcfg,
                                       append=not first)

    def _sample_key(self):
        import jax
        self._step_counter += 1
        return jax.random.fold_in(jax.random.key(self._seed),
                                  self._step_counter)

    def _sample_vectors(self):
        """Per-slot sampling parameter vectors for the shared decode
        step (idle/greedy rows: temp 0 = argmax). Cached — they change
        only on admit/release, not per token, so the per-step loop pays
        zero host->device transfers for them."""
        if self._sample_vec is None:
            import jax.numpy as jnp
            temps, tks, tps = [], [], []
            for s in self.slots:
                temps.append(s["temperature"] if s else 0.0)
                tks.append(s["top_k"] if s else 0)
                tps.append(s["top_p"] if s else 1.0)
            self._sample_vec = (jnp.array(temps, jnp.float32),
                                jnp.array(tks, jnp.int32),
                                jnp.array(tps, jnp.float32))
        return self._sample_vec

    def _arm_or_finish(self, i, item):
        """Prefill complete: first token comes off the last piece's
        logits (greedy fast path, or the request's sampling params);
        one-token requests answer immediately."""
        import jax
        import jax.numpy as jnp

        self._store_prefix(i, item)   # slot row holds the full prompt's KV
        if self._paged and item.get("_kv_key"):
            self._kv_export(i, item)  # disagg: park the prompt KV for /kv
        logits = item.pop("_last_logits")
        if item["temperature"] == 0.0:
            tok = int(jax.device_get(jnp.argmax(logits[0])))
        else:
            from ..batching import rowwise_pick
            tok = int(jax.device_get(rowwise_pick(
                logits,
                jnp.array([item["temperature"]], jnp.float32),
                jnp.array([item["top_k"]], jnp.int32),
                jnp.array([item["top_p"]], jnp.float32),
                self._sample_key())[0]))
        item["stream"] = [tok]
        item["last"] = tok
        if item["max_new"] <= 1:
            item["out"] = item["stream"]
            item["done"].set()
            self._release_slot(i)     # also frees (paged) blocks
        else:
            self.slots[i] = item
            self._sample_vec = None

    def _prefill_tick(self) -> bool:
        """Feed ONE pending prompt piece (chunked mode). True if fed.
        Scans round-robin from a rotating cursor so a chunked prefill
        parked in a high slot can't be starved by a stream of new chunked
        admissions landing in lower-index slots."""
        n = len(self.slots)
        for off in range(n):
            i = (self._prefill_cursor + off) % n
            s = self.slots[i]
            if s is None or not (s.get("chunks") or s.get("dchunks")):
                continue
            if "_await" in s:
                # paged in-flight prefix share: the donor hasn't written
                # the shared positions yet — skip this slot (the donor's
                # own prefill progresses every tick, so this resolves;
                # acyclic because a follower only awaits an EARLIER
                # admission). The donor item dict outlives its slot, so
                # a released donor (prefill necessarily complete) passes.
                d_item, need = s["_await"]
                if d_item.get("_written", 0) < need:
                    continue
                del s["_await"]
                s["_written"] = need     # donor wrote [0, need) for us
            self._prefill_cursor = (i + 1) % n
            # no local error handling: the item is slot-resident, so a
            # crash propagating to _run hits _fail_all, which releases it
            if s.get("chunks"):
                piece = s["chunks"].pop(0)
                # a prefix-restored item must APPEND from its first piece
                # (the row already holds the restored prefix at its length)
                self._prefill_piece(i, s, piece,
                                    first=("_last_logits" not in s
                                           and not s.get("_restored")))
            if s.get("dchunks"):
                # one draft piece per tick too: the draft forward is cheap
                # next to the target's, and arming waits for both
                dpiece = s["dchunks"].pop(0)
                self._draft_prefill(i, dpiece,
                                    first=not s.get("_d_started"))
                s["_d_started"] = True
            if not s.get("chunks") and not s.get("dchunks"):
                s.pop("chunks", None)
                s.pop("dchunks", None)
                s.pop("_d_started", None)
                self._arm_or_finish(i, s)
            return True
        return False

    def _spec_round(self, active: list, toks) -> None:
        """One speculative round over the whole slot batch: draft proposes
        gamma per active row, target verifies all rows in one multi-token
        forward, per-row accept + cache rollback, emit 1..gamma+1 tokens
        per row. One host sync per round (the accept fetch) — speculative
        decoding amortizes the per-token dispatch/RTT like decode_chunk
        does, while also cutting target forwards per token."""
        import jax
        import jax.numpy as jnp

        from ..batching import (rowwise_spec_accept, slot_decode,
                                slot_spec_draft, spec_accept_greedy)
        slot_verify = self._fn_verify()        # dense or paged target
        dcfg, dparams = self._draft
        g = self.gamma
        act = jnp.array(active)
        sampling = any(s is not None and s.get("stream") is not None
                       and s["temperature"] > 0 for s in self.slots)
        if sampling:
            sample = (*self._sample_vectors(), self._sample_key())
            drafts, dlogp, self.d_cache = slot_spec_draft(
                dparams, toks, self.d_cache, act, dcfg, g, sample)
        else:
            drafts, dlogp, self.d_cache = slot_spec_draft(
                dparams, toks, self.d_cache, act, dcfg, g)
        blocks = jnp.concatenate([toks[:, None], drafts], axis=1)
        tlogits, self.cache = slot_verify(self.params, blocks, self.cache,
                                          act, self.config)
        if sampling:
            temps, tks, tps = self._sample_vectors()
            a, emit = rowwise_spec_accept(tlogits, drafts, dlogp, temps,
                                          tks, tps, self._sample_key())
        else:
            a, emit = spec_accept_greedy(tlogits, drafts)
        a_host, emit_host = jax.device_get((a, emit))  # ONE host sync
        # all-gamma-accepted rows are missing the draft's entry for the
        # last proposal (the draft never forwarded it) — one draft step
        # for exactly those rows fills it before the rollback
        fill = [bool(active[i]) and int(a_host[i]) == g
                for i in range(len(self.slots))]
        if any(fill):
            _, self.d_cache = slot_decode(dparams, drafts[:, -1],
                                          self.d_cache, jnp.array(fill),
                                          dcfg)
        # roll both caches back to exactly the accepted entries: target
        # wrote gamma+1 (keep 1+a); draft wrote gamma, +1 for filled rows
        self.cache["lengths"] = (self.cache["lengths"]
                                 - jnp.where(act, g - a, 0))
        self.d_cache["lengths"] = (
            self.d_cache["lengths"]
            - jnp.where(act, jnp.where(a == g, 0, g - 1 - a), 0))
        self.spec_rounds += 1
        for i, s in enumerate(self.slots):
            if not active[i]:
                continue
            take = min(1 + int(a_host[i]),
                       s["max_new"] - len(s["stream"]))
            s["stream"].extend(int(t) for t in emit_host[i, :take])
            s["last"] = s["stream"][-1]
            self.spec_proposed += g
            self.spec_accepted += int(a_host[i])
            self.spec_emitted += take
            if len(s["stream"]) >= s["max_new"]:
                s["out"] = s["stream"]
                s["done"].set()
                self._release_slot(i)

    def _has_waiters(self) -> bool:
        """Work is waiting to join (defers chunked decode so admission
        latency stays one step). Lock-step subclass overrides: its
        arrivals live in a broadcast-synced pending list, not the queue
        (queue timing would desync the ranks)."""
        return self._waiting is not None or not self.queue.empty()

    def _sync(self) -> int:
        """Per-tick prologue: 0 = leave the loop. The lock-step
        subclass overrides this with the cross-rank admission broadcast
        (one hook — the tick loop itself stays shared)."""
        return 0 if self._stop else 1

    def _loop(self):
        import time as _time

        fns = (self._fn_decode(), self._fn_decode_pick(),
               self._fn_decode_multi())
        while True:
            if self._sync() == 0:
                return
            if not self._tick(*fns):
                _time.sleep(0.002)

    def _chip_slice(self, tokens: int = 0):
        """Admission for one device chunk: the co-tenancy regulator's
        slice when this batcher shares its chip, else free."""
        if self._regulator is None:
            return contextlib.nullcontext()
        return self._regulator.slice(tokens=tokens)

    def _tick(self, slot_decode, decode_pick, decode_multi) -> bool:
        """One scheduler tick: admit, feed one prefill piece, one decode
        step (or spec round / decode chunk) for the active rows. Returns
        False when there was nothing to do (the loop sleeps).

        Every device dispatch runs inside a _chip_slice: on a shared
        chip the regulator admits chunks by share weight, and a waiting
        latency-class co-tenant both preempts at the chunk boundary and
        (via should_yield below) drops this batcher back to single-step
        chunks so the next boundary arrives one step away."""
        import jax
        import jax.numpy as jnp

        if self._paged:
            self._purge_kv_exports()
        with self._chip_slice():
            self._admit()
            fed = self._prefill_tick()      # one prompt piece per tick
        if self._trie is not None and self._sketch_dirty:
            self._refresh_sketch()
        # decodable = prefill finished (mid-prefill slots sit out the
        # step: their lengths must not advance)
        active = [s is not None and s.get("stream") is not None
                  for s in self.slots]
        if not any(active):
            return fed
        n_active = sum(active)
        toks = jnp.array(
            [s["last"] if active[i] else 0
             for i, s in enumerate(self.slots)], jnp.int32)
        if self._draft is not None:
            with self._chip_slice(tokens=n_active * (self.gamma + 1)):
                self._spec_round(active, toks)
            return True
        # chunked decode only when nothing is waiting to join (and no
        # prefill mid-flight — implied by `not fed`, which scanned all
        # slots) — otherwise single steps keep admission/interleave
        # latency at one step. The chunk size stays FIXED so exactly
        # one extra program exists: stream tails run masked passes
        # (bounded waste: < chunk steps per stream END, a few percent
        # of a long stream). The alternatives both measured worse on
        # chip: dropping to single steps pays a host sync per tail
        # token (the whole wall through a high-RTT link), and a
        # power-of-two chunk ladder pays one XLA compile per rung.
        chunk = self.decode_chunk
        # a contended shared chip also forces single steps: the latency
        # co-tenant's stall bound shrinks from one chunk to one step
        contended = (self._regulator is not None
                     and self._regulator.should_yield())
        idle = (chunk > 1 and not fed and not self._has_waiters()
                and not contended)
        # greedy fast path: no sampling row DECODING -> the
        # pure-argmax programs (no per-step full-vocab sort for
        # traffic that doesn't need it; a sampler still mid-prefill
        # has stream=None and must not tax the running greedy rows)
        sampling = any(s is not None and s.get("stream") is not None
                       and s["temperature"] > 0 for s in self.slots)
        if idle:
            remaining = jnp.array(
                [s["max_new"] - len(s["stream"]) if active[i] else 0
                 for i, s in enumerate(self.slots)], jnp.int32)
            with self._chip_slice(tokens=n_active * chunk):
                steps, self.cache = decode_multi(
                    self.params, toks, self.cache, jnp.array(active),
                    remaining, self.config, chunk,
                    sample=((*self._sample_vectors(), self._sample_key())
                            if sampling else None))
                steps = jax.device_get(steps)       # [chunk, slots]
            for i, s in enumerate(self.slots):
                if not active[i]:
                    continue
                take = min(chunk, s["max_new"] - len(s["stream"]))
                s["stream"].extend(int(t) for t in steps[:take, i])
                s["last"] = s["stream"][-1]
                if len(s["stream"]) >= s["max_new"]:
                    s["out"] = s["stream"]
                    s["done"].set()
                    self._release_slot(i)
            return True
        with self._chip_slice(tokens=n_active):
            if sampling:
                picked, self.cache = decode_pick(
                    self.params, toks, self.cache, jnp.array(active),
                    *self._sample_vectors(), self._sample_key(),
                    self.config)
                nxt = jax.device_get(picked)
            else:
                logits, self.cache = slot_decode(
                    self.params, toks, self.cache,
                    jnp.array(active), self.config)
                nxt = jax.device_get(jnp.argmax(logits, axis=-1))
        for i, s in enumerate(self.slots):
            if not active[i]:
                continue
            tok = int(nxt[i])
            s["stream"].append(tok)
            s["last"] = tok
            if len(s["stream"]) >= s["max_new"]:
                s["out"] = s["stream"]
                s["done"].set()
                # slot free; stale KV dead; (paged) blocks back to pool
                self._release_slot(i)
        return True


class _LockstepBatcher(_Batcher):
    """Continuous batching over a MULTI-PROCESS SPMD mesh (VERDICT r4
    next #6): every rank runs the IDENTICAL scheduler; rank 0 is the
    only one with real HTTP arrivals, and each tick begins with one
    broadcast of the newly-arrived requests (prompt tokens + budget +
    per-request sampling params) — after which every rank's scheduler
    state evolves deterministically, so all ranks issue the same jitted
    slot-ops in the same order on globally-sharded params: the SPMD
    contract, now per SCHEDULER TICK instead of per request. Concurrent
    streams share decode steps exactly like the single-host batcher
    (admission between steps, per-row budgets, chunked decode when no
    one is waiting).

    Determinism inventory (everything a tick's decisions read): the
    pending list (broadcast), slot occupancy and stream lengths (evolve
    from the pending list plus device results that are themselves
    identical under SPMD), the PRNG seed (broadcast at construction,
    folded with a lock-step counter), and decode_chunk/prefill_chunk
    (identical CLI flags). queue.empty() — the one timing-dependent
    input in the base loop — is replaced by the synced pending list
    (_has_waiters override).

    The single-host compositions ride along: the paged allocator,
    prefix store, and in-flight sharing are host bookkeeping driven
    ONLY by the synced pending list + SPMD device results, so their
    decisions replicate across ranks tick-for-tick; the paged pool and
    page tables are replicated global arrays (_build) that every rank
    mutates in the same order. --kv-quant likewise (same programs,
    int8 pools). Speculative rides too: the draft tree is built sharded
    on the same mesh (_serve_multihost), its slot cache replicates via
    _build, and accept/rollback reads SPMD-identical device results.
    restarts=0: a crash on one rank cannot be restarted in lock-step
    (the peers are parked in a collective nobody will complete) — fail
    every waiter and let the process-level supervisor restart the pod."""

    # at most this many admissions broadcast per tick (the rest stay in
    # rank 0's queue for later ticks — bounds the broadcast payload)
    BCAST_K = 4

    def __init__(self, config, params, slots: int, max_len: int, mesh,
                 rank: int, shard_kv: bool = False, **kw):
        """kw forwards the _Batcher composition knobs (prefill_chunk,
        decode_chunk, seed, kv_quant, kv_block, kv_pool_blocks,
        prefix_cache, draft, gamma) — the paged allocator, prefix store,
        and spec scheduler are deterministic functions of the synced
        pending list + SPMD device results, so they lock-step as-is;
        only cache CONSTRUCTION needs the mesh (see _build)."""
        self._mesh = mesh
        self._rank = rank
        self._shard_kv = shard_kv
        self._pending: list = []
        super().__init__(config, params, slots, max_len, restarts=0, **kw)

    def _build(self, init_fn, kv_sharded: bool = False):
        """Every cache (dense or paged pool, target or draft) must be a
        GLOBAL array (the jitted slot-ops mix it with the mesh-sharded
        params). Default: replicated — every rank holds the full cache,
        matmuls still run tp-sharded (the KV attend is the replicated
        part). shard_kv: the TARGET cache's K/V buffers (and their kv8
        scales) shard over tp on the kv-head axis (always axis ndim-2
        in every layout — dense [L,slots,T,Hkv,D], paged pool
        [L,blocks,blk,Hkv,D], scales [...,Hkv,1]), cutting per-rank
        cache HBM by tp: the attend runs on each rank's own heads (q is
        already head-sharded by the megatron wq), and the page tables /
        lengths stay replicated so the host allocator logic is
        untouched. The dryrun's S4 plan pins the HLO shape: no
        cache-sized collectives appear."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        repl = NamedSharding(self._mesh, PartitionSpec())
        if not (kv_sharded and self._shard_kv):
            return jax.jit(init_fn, out_shardings=repl)()
        from ..batching import kv_shard_specs
        out_shardings = kv_shard_specs(self._mesh,
                                       jax.eval_shape(init_fn))
        return jax.jit(init_fn, out_shardings=out_shardings)()

    def _has_waiters(self) -> bool:
        return self._waiting is not None or bool(self._pending)

    @property
    def queued(self) -> int:
        return (self.queue.qsize() + len(self._pending)
                + (self._waiting is not None))

    def _next_item(self):
        """Parked head-of-line item (paged admission short on blocks)
        first, exactly like the base — its parking decision was itself
        lock-step, so every rank re-offers it in the same order."""
        if self._waiting is not None:
            item, self._waiting = self._waiting, None
            return item
        return self._pending.pop(0) if self._pending else None

    def _fail_all(self, exc: Exception) -> None:
        super()._fail_all(exc)
        for it in self._pending:        # rank 0: real waiters live here
            it["error"] = exc
            it["done"].set()
        self._pending.clear()

    def _sync(self) -> int:
        """The per-tick broadcast: rank 0 encodes the tick's newly
        admitted-to-pending requests (or the stop op); every rank
        decodes the same payload into its pending list. Fixed shapes —
        one compiled broadcast program for the batcher's lifetime.

        Three invariants the encoding keeps:
        - drained requests enter _pending BEFORE the broadcast, so a
          broadcast failure propagating to _fail_all still releases
          their waiters (nothing is ever in neither queue nor pending);
        - rank 0 re-reads each request's sampling params from the f32
          wire arrays it built, so every rank — including rank 0 —
          gates on the SAME rounded values (a f64 temperature that
          rounds to f32 0.0 must pick the greedy program on all ranks,
          or the PRNG counters desync);
        - the per-tick drain is capped at free slots + 1 lookahead (not
          a flat BCAST_K), so a sustained overload backlogs in rank 0's
          queue — not replicated without bound into every rank's
          pending list."""
        import queue as _queue

        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.experimental import multihost_utils

        k, t = self.BCAST_K, self.max_len
        ints = np.zeros((2 + 3 * k,), np.int32)
        floats = np.zeros((2 * k,), np.float32)
        prompts = np.zeros((k, t), np.int32)
        items: list = []
        if self._rank == 0:
            if self._stop:
                ints[0] = 0
            else:
                ints[0] = 1
                free = sum(s is None for s in self.slots)
                budget = min(k, max(0, free + 1 - len(self._pending)))
                while len(items) < budget:
                    try:
                        items.append(self.queue.get_nowait())
                    except _queue.Empty:
                        break
                self._pending.extend(items)
                ints[1] = len(items)
                for j, it in enumerate(items):
                    p = np.asarray(jax.device_get(it["prompt"]), np.int32)
                    ints[2 + 3 * j:5 + 3 * j] = (p.shape[0], it["max_new"],
                                                 it["top_k"])
                    floats[2 * j:2 * j + 2] = (it["temperature"],
                                               it["top_p"])
                    prompts[j, :p.shape[0]] = p
        ints, floats, prompts = multihost_utils.broadcast_one_to_all(
            (ints, floats, prompts))
        if int(ints[0]) == 0:
            return 0
        if self._rank == 0:
            for j, it in enumerate(items):      # adopt the f32 wire values
                it["temperature"] = float(floats[2 * j])
                it["top_p"] = float(floats[2 * j + 1])
        else:
            for j in range(int(ints[1])):
                plen, mx, tk = (int(x) for x in ints[2 + 3 * j:5 + 3 * j])
                self._pending.append({
                    "prompt": jnp.asarray(prompts[j, :plen]),
                    "max_new": mx, "temperature": float(floats[2 * j]),
                    "top_k": tk, "top_p": float(floats[2 * j + 1]),
                    "done": threading.Event(), "out": None, "error": None})
        return 1

    def _run(self):
        """Crash liveness (no restart in lock-step — restarts=0): a
        rank-0 crash must still broadcast the stop op, or every
        follower parks forever in a broadcast nobody will source; a
        follower crash exits its process, which errors the peers'
        next collective and lands THEM here too. Either way every
        process leaves, so a pod-level supervisor sees the death."""
        try:
            self._loop()
        except Exception as e:  # noqa: BLE001 — device/XLA/collective
            import traceback
            traceback.print_exc()
            self._fail_all(e)
            self._stop = True
            if self._rank == 0:
                try:
                    self._sync()          # best-effort stop broadcast
                except Exception:  # noqa: BLE001 — peers may be gone
                    pass


class _Server:
    def __init__(self, config, params, kv_quant: bool = False,
                 draft: tuple = None, gamma: int = 4):
        self.config = config
        self.params = params
        self.kv_quant = kv_quant
        self.draft = draft             # (draft_config, draft_params) | None
        self.gamma = gamma
        self.batcher: _Batcher | None = None
        self.lock = threading.Lock()   # single-flight: one chip
        import jax
        self.n_params = sum(p.size for p in jax.tree.leaves(params))

    def generate(self, tokens, max_new: int, temperature: float,
                 top_k: int = 0, top_p: float = 1.0,
                 stats_out: dict | None = None, kv_key: str = "",
                 kv_import: dict | None = None):
        import jax
        import jax.numpy as jnp

        from ..infer import generate
        prompt = jnp.asarray(tokens, jnp.int32)
        if prompt.ndim != 2:
            raise ValueError("tokens must be [batch, prompt_len]")
        lo, hi = jax.device_get((jnp.min(prompt), jnp.max(prompt)))
        if hi >= self.config.vocab_size or lo < 0:
            raise ValueError("token id out of range")
        # continuous batching: single-sequence requests (greedy OR
        # sampling — per-request temperature/top-k/top-p ride the shared
        # decode step via rowwise_pick) join the running slot batch
        # WITHOUT the single-flight lock — concurrency is the whole
        # point; the batcher thread owns the cache
        if self.batcher is not None:
            if prompt.shape[0] == 1:
                return [self.batcher.submit(
                    prompt[0], int(max_new), temperature=float(temperature),
                    top_k=int(top_k), top_p=float(top_p),
                    stats_out=stats_out, kv_key=kv_key,
                    kv_import=kv_import)]
            # a multi-row request would run generate() concurrently with
            # the batcher's slot decode on the same chip — two full KV
            # caches + programs live at once, an OOM on a chip where
            # either mode alone fits. Refuse instead of racing for HBM.
            raise ValueError(
                "server runs in continuous-batching mode: send "
                "single-sequence requests (one row; greedy or sampling), "
                "or start without --batch-slots for multi-row batches")
        with self.lock:
            # speculative path: single sequence + a draft loaded. Greedy
            # is exactly the target-only greedy stream; sampling keeps the
            # draft speedup via rejection sampling (the marginal output
            # distribution is exactly the target-only sampling one).
            if self.draft is not None and prompt.shape[0] == 1:
                from ..infer import speculative_generate
                dcfg, dparams = self.draft
                out, _ = speculative_generate(
                    self.params, dparams, prompt, self.config, dcfg,
                    int(max_new), gamma=self.gamma,
                    kv_quant=self.kv_quant,
                    temperature=float(temperature),
                    top_k=int(top_k), top_p=float(top_p),
                    key=jax.random.key(int.from_bytes(
                        os.urandom(4), "big")))
            else:
                out = generate(self.params, prompt, self.config,
                               int(max_new),
                               temperature=float(temperature),
                               top_k=int(top_k), top_p=float(top_p),
                               kv_quant=self.kv_quant,
                               key=jax.random.key(int.from_bytes(
                                   os.urandom(4), "big")))
        return jax.device_get(out).tolist()


def _fetch_kv(source: str, key: str) -> "dict | None":
    """Decode side of the disaggregated handoff: pull the prompt KV a
    prefill replica exported (GET /kv on `source` = "host:port"). ANY
    failure — peer gone, export expired, malformed payload — returns
    None and the decode replica simply prefills from scratch; the
    handoff is a fast path, never a correctness dependency."""
    import base64
    from http.client import HTTPConnection

    import numpy as np
    try:
        host, _, port = source.rpartition(":")
        conn = HTTPConnection(host or "127.0.0.1", int(port), timeout=5)
        try:
            conn.request("GET", "/kv?key=" + key)
            payload = json.loads(conn.getresponse().read() or b"{}")
        finally:
            conn.close()
        data = payload.get("data") or {}
        if payload.get("code") != 200 or not data.get("tokens"):
            return None
        bufs = {
            name: np.frombuffer(
                base64.b64decode(d["b64"]),
                dtype=np.dtype(d["dtype"])).reshape(d["shape"])
            for name, d in (data.get("bufs") or {}).items()}
        return {"tokens": data["tokens"], "bufs": bufs}
    except Exception:  # noqa: BLE001 — degrade to full prefill, always
        return None


def _handler_for(srv: _Server, model_name: str, admit_queue: int = 0):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # keep-alive envelope responses flush headers and body as two
        # segments; a fronting gateway pays Nagle + delayed-ACK per
        # request without this (same setting as the control plane's
        # server/http.py)
        disable_nagle_algorithm = True

        def log_message(self, *a):
            pass

        def _send(self, code: int, msg: str, data,
                  extra: "dict | None" = None):
            payload = json.dumps(
                {"code": code, "msg": msg, "data": data}).encode()
            self.send_response(200)     # control-plane envelope style
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            # W3C trace continuity: echo the caller's traceparent so a
            # fronting gateway/worker can confirm which trace this
            # response belongs to (replica-side time stitches into the
            # caller's span via X-TDAPI-Queue-Wait-Ms)
            tp = self.headers.get("traceparent")
            if tp:
                self.send_header("traceparent", tp)
            # replica-side admission surface: a fronting gateway reads
            # the batcher's slot/queue state off EVERY response instead
            # of polling /healthz between requests (admit-on-slot-free)
            b = srv.batcher
            if b is not None:
                self.send_header("X-TDAPI-Slots", str(len(b.slots)))
                self.send_header("X-TDAPI-Active",
                                 str(sum(s is not None for s in b.slots)))
                self.send_header("X-TDAPI-Queued", str(b.queued))
                if b.queue_wait_ewma_ms is not None:
                    self.send_header("X-TDAPI-Queue-Wait-EWMA-Ms",
                                     str(round(b.queue_wait_ewma_ms, 3)))
                # KV-affinity advertisement: the fronting worker/gateway
                # folds the prefix sketch + occupancy off EVERY response
                # into its routing state — zero extra round-trips
                if b._trie is not None:
                    sketch_hex, occ, _ = b._sketch_pub
                    self.send_header("X-TDAPI-KV-Sketch", sketch_hex)
                    self.send_header("X-TDAPI-KV-Occ", str(occ))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(payload)

        def do_GET(self):
            if self.path == "/healthz":
                data = {
                    "model": model_name,
                    "params": srv.n_params,
                    "vocab": srv.config.vocab_size,
                    "maxSeqLen": srv.config.max_seq_len,
                }
                if srv.batcher is not None:
                    b = srv.batcher
                    data["batching"] = {
                        "slots": len(b.slots),
                        "active": sum(s is not None for s in b.slots),
                        "queued": b.queued,
                        "maxLen": b.max_len,
                        "alive": b.alive,
                        "prefixHits": b.prefix_hits,
                        "queueWait": {
                            "count": b.queue_wait_count,
                            "totalMs": round(b.queue_wait_ms_total, 3),
                            "lastMs": (round(b.last_queue_wait_ms, 3)
                                       if b.last_queue_wait_ms is not None
                                       else None),
                            "ewmaMs": (round(b.queue_wait_ewma_ms, 3)
                                       if b.queue_wait_ewma_ms is not None
                                       else None),
                        },
                    }
                    if b._trie is not None:
                        sketch_hex, occ, entries = b._sketch_pub
                        data["batching"]["prefixCache"] = {
                            "entries": entries,
                            "blocks": occ,
                            "evictions": b.prefix_evictions,
                            "kvExports": len(b._kv_exports),
                            "handoffsIn": b.kv_handoffs_in,
                            "sketch": sketch_hex,
                        }
                    if b._draft is not None:
                        data["batching"]["speculative"] = {
                            "gamma": b.gamma,
                            "rounds": b.spec_rounds,
                            "proposed": b.spec_proposed,
                            "accepted": b.spec_accepted,
                            "emitted": b.spec_emitted,
                            # fraction of PROPOSED draft tokens accepted
                            # (a round proposes gamma per ACTIVE row, so
                            # rounds*gamma under-counts the denominator
                            # whenever >1 row is active)
                            "acceptRate": round(
                                b.spec_accepted
                                / max(b.spec_proposed, 1), 3),
                        }
                    if b._paged:
                        data["batching"]["paged"] = {
                            "blockSize": b.kv_block,
                            "poolBlocks": b.kv_pool_blocks,
                            "freeBlocks": b._alloc.free_blocks,
                        }
                self._send(200, "Success", data)
            elif self.path.startswith("/kv?") or self.path == "/kv":
                # disaggregated handoff fetch: a decode replica pulls the
                # prompt KV a prefill replica exported (once; TTL-purged
                # server-side, so a decode peer that dies mid-handoff
                # can never pin pool blocks here)
                import base64
                from urllib.parse import parse_qs, urlparse
                b = srv.batcher
                key = (parse_qs(urlparse(self.path).query)
                       .get("key") or [""])[0]
                e = (b.kv_take(key)
                     if b is not None and b._paged else None)
                if e is None:
                    self._send(404, "kv export not found", None)
                    return
                bufs = {
                    name: {"dtype": arr.dtype.name,
                           "shape": list(arr.shape),
                           "b64": base64.b64encode(
                               arr.tobytes()).decode()}
                    for name, arr in e["bufs"].items()}
                self._send(200, "Success",
                           {"tokens": list(e["tokens"]), "len": e["len"],
                            "bufs": bufs})
            else:
                self._send(404, "route not found", None)

        def do_POST(self):
            if self.path != "/generate":
                self._send(404, "route not found", None)
                return
            # --admit-queue: shed BEFORE submitting once the batcher's
            # wait line is past the bound — the 429 (+ X-TDAPI-Shed) tells
            # a fronting gateway to route elsewhere / back off, instead of
            # parking one more waiter on a saturated replica
            b = srv.batcher
            if (admit_queue > 0 and b is not None
                    and b.queued >= admit_queue):
                self._send(429, "replica queue full", None,
                           extra={"Retry-After": "1", "X-TDAPI-Shed": "1"})
                return
            try:
                length = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(length) or b"{}")
                tokens = body["tokens"]
                max_new = int(body.get("max_new", 16))
                temperature = float(body.get("temperature", 0.0))
                top_k = int(body.get("top_k", 0))
                top_p = float(body.get("top_p", 1.0))
                if max_new < 1:
                    raise ValueError("max_new must be >= 1")
                if not 0.0 < top_p <= 1.0:
                    raise ValueError("top_p must be in (0, 1]")
                if top_k < 0:
                    raise ValueError("top_k must be >= 0")
                if not 0.0 <= temperature <= 10.0:
                    raise ValueError("temperature must be in [0, 10]")
                # on the non-batcher path sampling params are jit-STATIC:
                # quantize them so a client sweeping float values can't
                # force a fresh XLA compile per request (each held under
                # the single-flight lock) or grow the program cache
                # without bound — bounded buckets: 201 temperatures x
                # 20 top_p x 129 top_k. The batcher path takes them as
                # DATA (rowwise_pick) with zero compile variety, so it
                # serves exactly what the client asked.
                if srv.batcher is None:
                    temperature = round(temperature * 20) / 20
                    top_p = round(top_p * 20) / 20 or 0.05
                    top_k = min(top_k, 128)
                # disaggregated handoff contract (paged batcher only):
                # X-TDAPI-Phase: prefill + X-TDAPI-KV-Key -> run ONLY the
                # prefill (one token), export the prompt KV under the key;
                # X-TDAPI-KV-Source + X-TDAPI-KV-Key -> fetch that export
                # from the prefill replica and resume without re-prefill.
                # Any fetch failure degrades to a plain full request.
                hdr_key = self.headers.get("X-TDAPI-KV-Key") or ""
                kv_src = self.headers.get("X-TDAPI-KV-Source") or ""
                phase = self.headers.get("X-TDAPI-Phase") or ""
                kv_key, kv_import = "", None
                if hdr_key and b is not None and b._paged:
                    if phase == "prefill":
                        kv_key, max_new = hdr_key, 1
                    elif kv_src:
                        kv_import = _fetch_kv(kv_src, hdr_key)
                stats: dict = {}
                out = srv.generate(tokens, max_new, temperature,
                                   top_k=top_k, top_p=top_p,
                                   stats_out=stats, kv_key=kv_key,
                                   kv_import=kv_import)
                extra = None
                if "queueWaitMs" in stats:
                    # per-request batcher queue wait: the span-event
                    # source a fronting worker stitches into its
                    # gateway.forward span
                    extra = {"X-TDAPI-Queue-Wait-Ms":
                             str(stats["queueWaitMs"])}
                self._send(200, "Success", {"tokens": out}, extra=extra)
            except (KeyError, TypeError, ValueError) as e:
                self._send(400, f"bad request: {e}", None)

    return Handler


class _MultihostServer:
    """Rank-0 facade the HTTP handler drives in multi-host mode: generate
    enqueues the request for the lock-step engine loop and blocks on its
    result (single-flight falls out of the single consumer)."""

    def __init__(self, config, n_params: int, work_q, kv_quant: bool,
                 b_max: int, t_max: int):
        self.config = config
        self.n_params = n_params
        self.kv_quant = kv_quant
        self.batcher = None          # healthz compatibility
        self.draft = None
        self._q = work_q
        self.b_max = b_max
        self.t_max = t_max

    def generate(self, tokens, max_new: int, temperature: float,
                 top_k: int = 0, top_p: float = 1.0,
                 stats_out: dict | None = None):
        import jax
        import jax.numpy as jnp
        prompt = jnp.asarray(tokens, jnp.int32)
        if prompt.ndim != 2:
            raise ValueError("tokens must be [batch, prompt_len]")
        # request-shape limits reject HERE (a 400 to the client) — an
        # invalid item must never reach the engine loop, where a rank-0
        # failure before the broadcast would strand the other ranks, and
        # an unbounded max_new would park every rank in one scan for the
        # single-flight engine's lifetime
        if prompt.shape[0] > self.b_max or prompt.shape[1] >= self.t_max:
            raise ValueError(f"batch <= {self.b_max} and prompt < "
                             f"{self.t_max} in multihost mode")
        if prompt.shape[1] + int(max_new) > self.t_max:
            raise ValueError(
                f"prompt + max_new exceeds the model's max_seq_len "
                f"({self.t_max})")
        lo, hi = jax.device_get((jnp.min(prompt), jnp.max(prompt)))
        if hi >= self.config.vocab_size or lo < 0:
            raise ValueError("token id out of range")
        item = {"prompt": prompt, "max_new": int(max_new),
                "temperature": float(temperature), "top_k": int(top_k),
                "top_p": float(top_p),
                "done": threading.Event(), "out": None, "error": None}
        self._q.put(item)
        item["done"].wait()
        if item["error"] is not None:
            raise RuntimeError(f"multihost engine failed: {item['error']}")
        return item["out"]


def _serve_multihost(args, config) -> int:
    """Lock-step SPMD serving over a multi-process cluster (SURVEY §5.8,
    VERDICT r3 weak #6): every process builds the SAME sharded params
    over one global mesh (tp over ICI); rank 0 owns the HTTP endpoint
    and BROADCASTS each request (tokens + sampling params + a shared PRNG
    seed) to the other ranks, so all processes execute the identical
    jitted generate — the SPMD contract. Non-zero ranks run the engine
    loop only. Shutdown broadcasts a sentinel so no rank is left blocked
    in a collective."""
    import queue as _queue

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils

    from ..infer import generate
    from ..parallel.mesh import MeshPlan, best_tp_for
    from ..train import Trainer, restore_checkpoint

    n_dev = jax.device_count()
    tp = args.tp or best_tp_for(n_dev)
    trainer = Trainer.create(config, MeshPlan.auto(n_dev, tp=tp))
    if args.checkpoint:
        # abstract-template restore: orbax reshards the checkpoint onto
        # THIS cluster's mesh, whatever shape the writer's mesh had
        abstract = trainer.abstract_state(jax.random.key(0))
        state, step = restore_checkpoint(os.path.abspath(args.checkpoint),
                                         abstract)
        print(f"restored checkpoint step {step} (sharded)", flush=True)
        params = state["params"]
    else:
        params = trainer.init(jax.random.key(0))["params"]
    params = _maybe_ungroup(params, config)
    n_params = sum(p.size for p in jax.tree.leaves(params))
    rank = jax.process_index()
    b_max, t_max = 8, config.max_seq_len

    if args.batch_slots > 0:
        if args.shard_kv:
            n_kv = getattr(config, "n_kv_heads", 0) or config.n_heads
            if n_kv % tp:
                raise SystemExit(
                    f"--shard-kv needs n_kv_heads ({n_kv}) divisible "
                    f"by tp ({tp})")
        draft = None
        if args.draft_config:
            from ..models import named_config
            dcfg = named_config(args.family, args.draft_config)
            if dcfg.vocab_size != config.vocab_size:
                raise SystemExit("draft and target must share a vocab")
            # validated here, next to the --shard-kv check, so a draft
            # whose head counts don't divide the target's tp dies with a
            # pointed message instead of a raw mesh/sharding error out
            # of Trainer.create
            d_kv = getattr(dcfg, "n_kv_heads", 0) or dcfg.n_heads
            if dcfg.n_heads % tp or d_kv % tp:
                raise SystemExit(
                    f"--draft-config {args.draft_config!r} needs n_heads "
                    f"({dcfg.n_heads}) and n_kv_heads ({d_kv}) divisible "
                    f"by the target's tp ({tp}); pick a draft config "
                    f"with compatible head counts or lower --tp")
            dtrainer = Trainer.create(dcfg, MeshPlan.auto(n_dev, tp=tp))
            if args.draft_checkpoint:
                abstract = dtrainer.abstract_state(jax.random.key(0))
                dstate, dstep = restore_checkpoint(
                    os.path.abspath(args.draft_checkpoint), abstract)
                print(f"restored draft checkpoint step {dstep} (sharded)",
                      flush=True)
                dparams = dstate["params"]
            else:
                # key(1), not key(0): a fresh-init draft under the
                # target's key would BE the fresh-init target whenever
                # the two share a named config (every harness run) —
                # real deployments pass --draft-checkpoint
                dparams = dtrainer.init(jax.random.key(1))["params"]
            draft = (dcfg, _maybe_ungroup(dparams, dcfg))
        return _serve_multihost_batched(args, config, trainer, params,
                                        rank, draft)

    work_q: "_queue.Queue" = _queue.Queue()
    httpd = None
    if rank == 0:
        srv = _MultihostServer(config, n_params, work_q, args.kv_quant,
                               b_max, t_max)
        name = f"{args.family}/{args.config}"
        httpd = ThreadingHTTPServer((args.host, args.port),
                                    _handler_for(srv, name))
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        print(f"multihost serving {name} ({n_params:,} params) on "
              f"{args.host}:{httpd.server_address[1]} — rank 0 of "
              f"{jax.process_count()}, mesh tp={tp} over {n_dev} devices",
              flush=True)
    else:
        print(f"multihost engine rank {rank}/{jax.process_count()} "
              "following", flush=True)

    def engine_round(item) -> None:
        """One broadcast + one lock-step generate. item is None on
        follower ranks (they receive everything from rank 0)."""
        if item is not None:
            p = np.asarray(jax.device_get(item["prompt"]), np.int32)
            b, t = p.shape
            pad = np.zeros((b_max, t_max), np.int32)
            pad[:b, :t] = p
            ints = np.array([1, b, t, item["max_new"], item["top_k"],
                             int.from_bytes(os.urandom(3), "big")],
                            np.int32)
            floats = np.array([item["temperature"], item["top_p"]],
                              np.float32)
        else:
            pad = np.zeros((b_max, t_max), np.int32)
            ints = np.zeros((6,), np.int32)
            floats = np.zeros((2,), np.float32)
        ints, floats, pad = multihost_utils.broadcast_one_to_all(
            (ints, floats, pad))
        op, b, t, max_new, top_k, seed = (int(x) for x in ints)
        if op == 0:
            return "stop"
        prompt = jnp.asarray(pad[:b, :t])
        with trainer.mesh:
            out = generate(params, prompt, config, max_new,
                           temperature=float(floats[0]), top_k=top_k,
                           top_p=float(floats[1]),
                           kv_quant=args.kv_quant,
                           key=jax.random.key(seed))
            out = jax.device_get(out)
        if item is not None:
            item["out"] = np.asarray(out).tolist()
        return None

    try:
        while True:
            if rank == 0:
                item = work_q.get()
                if item is None:              # shutdown sentinel
                    engine_round(None)        # broadcast op=0
                    break
                try:
                    if engine_round(item) == "stop":
                        break
                except Exception as e:  # noqa: BLE001 — surface to client
                    item["error"] = e
                    # the followers may be waiting in (or past) this
                    # round's collective; a best-effort sentinel keeps a
                    # rank-0 failure from stranding them in a broadcast
                    # nobody will complete
                    try:
                        engine_round(None)
                    except Exception:  # noqa: BLE001
                        pass
                    raise
                finally:
                    item["done"].set()
            else:
                if engine_round(None) == "stop":
                    break
    except KeyboardInterrupt:
        if rank == 0:
            engine_round(None)
    finally:
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
    return 0


def _serve_multihost_batched(args, config, trainer, params, rank,
                             draft=None) -> int:
    """Lock-step CONTINUOUS BATCHING across the multi-process cluster:
    every rank constructs the same _LockstepBatcher (sharded params,
    replicated global slot cache, broadcast PRNG seed); rank 0 owns the
    HTTP endpoint and its queue; each scheduler tick broadcasts the new
    admissions so all ranks advance every active stream together —
    concurrent requests share decode steps instead of serializing
    through the single-flight engine."""
    import jax
    import numpy as np
    from jax.experimental import multihost_utils

    # ONE seed for the whole pod (rank-local urandom would diverge the
    # SPMD sampling programs)
    seed = int(multihost_utils.broadcast_one_to_all(
        np.array([int.from_bytes(os.urandom(4), "big")], np.uint32))[0])
    try:
        batcher = _LockstepBatcher(
            config, params, slots=args.batch_slots,
            max_len=args.batch_max_len or config.max_seq_len,
            mesh=trainer.mesh, rank=rank,
            prefill_chunk=args.batch_prefill_chunk,
            decode_chunk=args.decode_chunk, seed=seed,
            kv_quant=args.kv_quant, kv_block=args.kv_block,
            kv_pool_blocks=args.kv_pool,
            prefix_cache=args.prefix_cache,
            draft=draft, gamma=args.gamma, shard_kv=args.shard_kv)
    except ValueError as e:
        raise SystemExit(str(e))
    if rank != 0:
        print(f"multihost batching engine rank {rank}/"
              f"{jax.process_count()} following", flush=True)
        batcher.thread.join()
        return 0 if batcher._dead is None else 1
    srv = _Server(config, params)
    srv.batcher = batcher
    name = f"{args.family}/{args.config}"
    httpd = ThreadingHTTPServer((args.host, args.port),
                                _handler_for(srv, name))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    mode = (f"paged ({batcher.kv_pool_blocks} x {args.kv_block} "
            f"token blocks)" if args.kv_block else "dense")
    if args.shard_kv:
        mode += ", tp-sharded"
    spec = (f", speculative (draft {args.draft_config}, gamma "
            f"{args.gamma})" if draft else "")
    print(f"multihost continuous batching {name} "
          f"({srv.n_params:,} params) on {args.host}:"
          f"{httpd.server_address[1]} — {args.batch_slots} slots x "
          f"{batcher.max_len} tokens, {mode} KV{spec}, rank 0 of "
          f"{jax.process_count()}", flush=True)
    # the main thread tracks the SCHEDULER, not the HTTP server: if the
    # lock-step loop dies, rank 0 must exit (not keep answering every
    # request with "batcher unavailable" while a supervisor sees a
    # healthy process)
    try:
        batcher.thread.join()
    except KeyboardInterrupt:
        pass
    finally:
        batcher.close()     # broadcasts the stop op: followers exit too
        httpd.shutdown()
        httpd.server_close()
    return 0 if batcher._dead is None else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--family", default="llama", choices=["llama", "moe"])
    p.add_argument("--config", default="tiny",
                   help="named config for the family (models.NAMED_CONFIGS; "
                        "e.g. tiny, mini, 250m, llama3_8b, mixtral_8x7b)")
    p.add_argument("--checkpoint", default="",
                   help="orbax checkpoint dir (e.g. the training workload's "
                        "<workdir>/checkpoints); fresh init when empty")
    p.add_argument("--quantize", default="", choices=["", "w8", "w8a8"],
                   help="int8 post-load quantization of the matmul weights "
                        "(ops/quant.py): w8 = weight-only (HBM-bound "
                        "decode), w8a8 = +dynamic activation int8 (MXU)")
    p.add_argument("--host-load", action="store_true",
                   help="load/init the model on HOST memory and stream "
                        "per-leaf int8 quantization to the chip — serves "
                        "models whose bf16 weights exceed HBM (llama3_8b "
                        "= 16GB bf16 -> ~8GB int8 on a 16GB v5e); "
                        "requires --quantize")
    p.add_argument("--kv-quant", action="store_true",
                   help="int8 KV cache: half the decode-loop HBM traffic "
                        "(per-token-per-head scales, dequantized in the "
                        "attend loop)")
    p.add_argument("--draft-config", default="",
                   help="named config of a draft model for speculative "
                        "decoding. Alone: B=1 requests (greedy stream "
                        "bit-exact; sampling exact via rejection "
                        "sampling). With --batch-slots: speculative "
                        "rounds run INSIDE the continuous batcher (per-"
                        "slot proposals, one shared verify forward, "
                        "same exactness per row); composes with "
                        "--kv-block (block-aware verify)")
    p.add_argument("--draft-checkpoint", default="",
                   help="orbax checkpoint for the draft (fresh init when "
                        "empty — useful only for testing)")
    p.add_argument("--gamma", type=int, default=4,
                   help="speculative proposal length per round")
    p.add_argument("--batch-slots", type=int, default=0,
                   help="continuous batching: N cache slots; greedy "
                        "single-sequence requests join the running batch "
                        "between decode steps (0 = off)")
    p.add_argument("--batch-max-len", type=int, default=0,
                   help="slot cache length (default: the model's "
                        "max_seq_len)")
    p.add_argument("--batch-prefill-chunk", type=int, default=0,
                   help="chunked prefill: feed prompts in pieces of N "
                        "tokens interleaved with decode steps, so a long "
                        "prompt doesn't stall running streams (0 = whole "
                        "prompt at once)")
    p.add_argument("--prefix-cache", type=int, default=0,
                   help="keep the KV of the last N distinct prompts; a "
                        "request extending a cached prompt prefills only "
                        "the suffix (system-prompt reuse; 0 = off). With "
                        "paged KV (--kv-block) the reuse is ZERO-COPY: "
                        "shared blocks enter the new request's page table")
    p.add_argument("--kv-block", type=int, default=0,
                   help="PAGED slot cache: block size in tokens — slots "
                        "share a block pool instead of dense slots x "
                        "max_len reservations; admission waits on free "
                        "blocks (0 = dense). Paged admission also shares "
                        "block-aligned common prompt prefixes with "
                        "IN-FLIGHT requests zero-copy (a burst of "
                        "identical prompts allocates ~one prompt's "
                        "blocks), independent of --prefix-cache")
    p.add_argument("--kv-pool", type=int, default=0,
                   help="paged pool size in blocks (default: full "
                        "capacity, slots x ceil(max_len/block) + scratch; "
                        "shrink to cap KV HBM)")
    p.add_argument("--decode-chunk", type=int, default=1,
                   help="decode up to N steps per host sync as one "
                        "device-side scan when no request is waiting to "
                        "join (amortizes per-token dispatch/RTT; 1 = "
                        "sync every step)")
    p.add_argument("--tp", type=int, default=0,
                   help="tensor-parallel width for MULTI-HOST serving "
                        "(0 = auto); single-host serving ignores it")
    p.add_argument("--shard-kv", action="store_true",
                   help="multihost batching: shard the slot/paged KV "
                        "cache over tp on the kv-head axis instead of "
                        "replicating it — per-rank cache HBM drops by "
                        "tp (requires n_kv_heads %% tp == 0)")
    p.add_argument("--admit-queue", type=int, default=0,
                   help="replica-side admission bound: /generate sheds "
                        "429 (+ X-TDAPI-Shed) once the batcher's queue "
                        "is this deep, so a fronting gateway re-routes "
                        "instead of stacking waiters (0 = never shed)")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=0,
                   help="0 = the control plane's granted port ($PORT from "
                        "the process substrate), falling back to 8000")
    args = p.parse_args(argv)
    if not args.port:
        args.port = int(os.environ.get("PORT", "8000"))

    from ..models import named_config
    from ..parallel.mesh import MeshPlan
    from ..train import Trainer

    try:
        config = named_config(args.family, args.config)
    except KeyError as e:
        p.error(str(e))

    # multi-host: a spanning grant's env contract describes the cluster —
    # join it BEFORE touching any jax API (same flow as the training
    # workload), then run the lock-step SPMD serving engine
    from ..distributed import maybe_initialize_from_env
    cluster = maybe_initialize_from_env()
    if cluster is not None:
        for flag, msg in (
                (args.quantize, "--quantize"),
                (args.host_load, "--host-load")):
            if flag:
                raise SystemExit(
                    f"{msg} is single-host serving for now; the "
                    "multi-host engine runs plain sharded generate "
                    "(drop the flag, or serve per-host)")
        if args.draft_config and not args.batch_slots:
            raise SystemExit(
                "--draft-config in multihost mode runs inside the "
                "lock-step batcher (per-slot proposals, shared sharded "
                "verify) — add --batch-slots N")
        if args.shard_kv and not args.batch_slots:
            raise SystemExit(
                "--shard-kv shards the batching scheduler's cache; it "
                "needs --batch-slots N")
        if not args.batch_slots and (args.prefix_cache or args.kv_block
                                     or args.kv_pool):
            raise SystemExit(
                "--prefix-cache/--kv-block/--kv-pool configure the "
                "batching scheduler; they need --batch-slots N "
                "(multihost or not)")
        return _serve_multihost(args, config)
    if args.shard_kv:
        raise SystemExit(
            "--shard-kv is multihost serving (the single-host cache "
            "has no mesh to shard over)")

    import jax
    if args.host_load:
        if not args.quantize:
            raise SystemExit("--host-load exists to serve models whose "
                             "bf16 weights exceed HBM; it requires "
                             "--quantize w8|w8a8")
        from ..models import family_for
        from ..ops.quant import quantize_params_streaming
        # the bf16 tree never touches the chip: init/restore on HOST
        # (raw orbax restore lands on host; fresh init runs on the cpu
        # backend — params only, no throwaway optimizer state), then
        # stream per-leaf int8 to the device — HBM holds the int8 tree
        # plus one leaf in flight
        if args.checkpoint:
            from ..train import restore_checkpoint
            state, step = restore_checkpoint(os.path.abspath(
                args.checkpoint))
            print(f"restored checkpoint step {step} (host)", flush=True)
            host = state["params"]
        else:
            with jax.default_device(jax.devices("cpu")[0]):
                # jit the init: XLA:CPU parallelizes the 8B random init
                # that eager mode would grind through single-threaded
                host = jax.jit(lambda k: family_for(config).init_params(
                    config, k))(jax.random.key(0))
        host = _maybe_ungroup(host, config)
        params = quantize_params_streaming(host, args.quantize,
                                           device=jax.devices()[0])
        del host
        print(f"host-loaded + streamed int8 ({args.quantize}) to "
              f"{jax.devices()[0].device_kind}", flush=True)
    else:
        trainer = Trainer.create(config, MeshPlan(),
                                 devices=jax.devices()[:1])
        params = _maybe_ungroup(_load_params(trainer, args.checkpoint),
                                config)
        if args.quantize:
            from ..ops.quant import quantize_params
            # donate the dense tree so the bf16 params and the int8 copy
            # are not both fully live during the convert
            params = jax.jit(lambda p: quantize_params(p, args.quantize),
                             donate_argnums=0)(params)
            print(f"quantized matmul weights to int8 ({args.quantize})",
                  flush=True)
    draft = None
    if args.draft_config:
        dcfg = named_config(args.family, args.draft_config)
        dtrainer = Trainer.create(dcfg, MeshPlan(), devices=jax.devices()[:1])
        # fresh-init drafts use key(1), matching the multihost path: under
        # the target's key(0) a same-named-config draft would BE the
        # target (trivial 100% acceptance in every harness run)
        dparams = _maybe_ungroup(
            _load_params(dtrainer, args.draft_checkpoint, init_key=1), dcfg)
        if dcfg.vocab_size != config.vocab_size:
            raise SystemExit("draft and target must share a vocab")
        draft = (dcfg, dparams)
        print(f"speculative decoding armed: draft {args.draft_config}, "
              f"gamma {args.gamma}", flush=True)
    srv = _Server(config, params, kv_quant=args.kv_quant, draft=draft,
                  gamma=args.gamma)
    reg_tenant = None
    if args.batch_slots > 0:
        # --draft-config composes: the batcher runs speculative rounds
        # over the whole slot batch (per-slot proposals, one shared
        # verify forward; greedy rows bit-exact, sampling rows exact via
        # per-row rejection sampling). --kv-quant composes (int8 slot
        # caches, both models). --kv-block composes (paged_verify writes
        # each row's gamma+1 tokens through its page table; admission
        # reserves the verify-overshoot headroom). decode_chunk is
        # superseded in speculative mode: a spec round already emits up
        # to gamma+1 tokens per host sync.
        # fractional co-tenancy: a share-granted container (control plane
        # injects TDAPI_TPU_SHARES/TDAPI_PRIORITY) registers with the
        # chip's regulator so its decode chunks time-slice against
        # co-tenants by share weight
        from .. import regulator as _regmod
        reg_tenant = _regmod.tenant_from_env()
        if reg_tenant is not None:
            print(f"chip co-tenancy: weight {reg_tenant.weight}, "
                  f"class {reg_tenant.priority}", flush=True)
        try:
            srv.batcher = _Batcher(config, params, slots=args.batch_slots,
                                   max_len=args.batch_max_len
                                   or config.max_seq_len,
                                   prefill_chunk=args.batch_prefill_chunk,
                                   prefix_cache=args.prefix_cache,
                                   kv_quant=args.kv_quant,
                                   kv_block=args.kv_block,
                                   kv_pool_blocks=args.kv_pool,
                                   decode_chunk=args.decode_chunk,
                                   draft=draft, gamma=args.gamma,
                                   regulator=reg_tenant)
        except ValueError as e:
            raise SystemExit(str(e))
        mode = (f"paged ({srv.batcher.kv_pool_blocks} x {args.kv_block} "
                f"token blocks)" if args.kv_block else "dense")
        spec = (f", speculative (draft {args.draft_config}, gamma "
                f"{args.gamma})" if draft else "")
        print(f"continuous batching: {args.batch_slots} slots x "
              f"{srv.batcher.max_len} tokens, {mode} KV{spec}", flush=True)
    elif args.prefix_cache:
        raise SystemExit("--prefix-cache lives in the batching scheduler; "
                         "it needs --batch-slots N")
    elif args.kv_block or args.kv_pool:
        raise SystemExit("--kv-block/--kv-pool configure the batching "
                         "scheduler's cache; they need --batch-slots N")

    name = f"{args.family}/{args.config}"
    httpd = ThreadingHTTPServer((args.host, args.port),
                                _handler_for(srv, name,
                                             admit_queue=args.admit_queue))
    print(f"serving {name} ({srv.n_params:,} params) on "
          f"{args.host}:{httpd.server_address[1]}", flush=True)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
        if reg_tenant is not None:
            # leave the chip's regulator clean: a replaced/restarted
            # version must not leave a dead tenant accumulating in the
            # process-global registry
            reg_tenant.unregister()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
