"""Mock model replica — the serving contract without the accelerator.

Speaks exactly the serving workload's HTTP surface (workloads/serve.py:
`GET /healthz` with the `batching` block, `POST /generate` with the
token-level envelope, the `X-TDAPI-*` admission headers), but the "model"
is a slot-bounded hold of --decode-ms per request instead of a jitted
decode loop. Exists so the GATEWAY control loop — routing, admission,
shedding, autoscale, clone-warm starts — can be exercised and priced
end-to-end over real processes and real HTTP without paying `import jax`
per replica (stdlib only: the warm pool absorbs the interpreter, and the
bench's router-overhead number prices the gateway, not the kernels).

Warm-start contract (the CoW-clone story, bench + e2e): startup costs
--init-ms once — simulating model load + first compile — then writes
--warm-mb of "weights" plus a `.model_ready` marker into the writable
layer. A replica whose layer was CLONED from a warm donor (gateway
scale-up) finds the marker and skips the init cost entirely: ready in
milliseconds, the same economics as a real replica inheriting its
donor's checkpoint/compile cache.
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import faults, kvaffinity

READY_MARKER = ".model_ready"

#: simulated prefix store capacity (distinct prompts whose "KV" is warm)
PREFIX_CAP = 32


def launch_cmd(repo_root: str, *args: str) -> list:
    """Container cmd that launches this module from `repo_root` on any
    cwd (the process substrate chdirs into the container rootfs before
    exec, so a bare `-m` lookup would miss the repo). The `-c` form is
    warm-pool-eligible and needs no PYTHON* env (which would force a
    cold spawn — backend/warmpool.py supports())."""
    import sys
    code = (f"import sys; sys.path.insert(0, {repo_root!r}); "
            "from gpu_docker_api_tpu.workloads.mock_model import main; "
            f"raise SystemExit(main({list(args)!r}))")
    return [sys.executable, "-u", "-c", code]


class _State:
    def __init__(self, slots: int, decode_ms: float, admit_queue: int,
                 prefill_token_ms: float = 0.0, kv_ttl: float = 30.0):
        self.slots = slots
        self.decode_ms = decode_ms
        self.admit_queue = admit_queue
        self.lock = threading.Lock()
        self.slot_sem = threading.Semaphore(slots)
        self.active = 0
        self.queued = 0
        self.served = 0
        self.shed = 0
        # KV serving contract (serve.py's paged-batcher surface, PR 18):
        # a bounded LRU of prompt tuples stands in for the prefix trie —
        # a request whose prompt extends a stored tuple skips that many
        # tokens of simulated prefill, which is what makes affinity
        # routing MEASURABLE over mocks (the bench's A/B lever)
        self.prefill_token_ms = prefill_token_ms
        self.kv_ttl = kv_ttl
        self.prefixes: OrderedDict = OrderedDict()   # prompt tuple -> True
        self.sketch_hex = kvaffinity.encode_sketch_hex(
            [0] * kvaffinity.SKETCH_WORDS)
        self.kv_exports: dict = {}    # key -> {"tokens": [...], "at": t}
        self.kv_fetches = 0
        self.handoffs_in = 0
        self.prefix_hits = 0
        self.qwait_ewma: float | None = None

    # -- prefix store (call under self.lock) --

    def store_prefix(self, row: tuple) -> None:
        self.prefixes.pop(row, None)
        self.prefixes[row] = True
        while len(self.prefixes) > PREFIX_CAP:
            self.prefixes.popitem(last=False)
        hashes: list = []
        for key in self.prefixes:
            hashes.extend(kvaffinity.chunk_hashes(key))
        self.sketch_hex = kvaffinity.encode_sketch_hex(
            kvaffinity.build_sketch(hashes))

    def prefix_hit(self, row: tuple) -> int:
        """Longest stored-prompt prefix of `row`, floored to whole
        chunks — the serve.py block-floor analogue."""
        best = 0
        for key in self.prefixes:
            if len(key) > best and row[:len(key)] == key:
                best = len(key)
        if best == len(row) and best > 0:
            best -= 1     # last position always recomputes (real logits)
        return (best // kvaffinity.CHUNK_TOKENS) * kvaffinity.CHUNK_TOKENS

    def purge_exports(self) -> None:
        now = time.monotonic()
        for k in [k for k, v in self.kv_exports.items()
                  if now - v["at"] > self.kv_ttl]:
            del self.kv_exports[k]


def _fetch_kv(source: str, key: str) -> "list | None":
    """Decode side of the mock handoff: pull a peer mock's /kv export.
    Returns the exported prompt token list, or None on ANY failure —
    same degrade-to-full-prefill contract as serve.py's _fetch_kv."""
    from http.client import HTTPConnection
    try:
        host, _, port = source.rpartition(":")
        conn = HTTPConnection(host or "127.0.0.1", int(port), timeout=5)
        try:
            conn.request("GET", "/kv?key=" + key)
            payload = json.loads(conn.getresponse().read() or b"{}")
        finally:
            conn.close()
        if payload.get("code") != 200:
            return None
        toks = (payload.get("data") or {}).get("tokens")
        return list(toks) if isinstance(toks, list) and toks else None
    # tdlint: disable=silent-swallow -- a failed fetch degrades to full prefill by contract
    except Exception:  # noqa: BLE001
        return None


def _handler_for(st: _State, model: str):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # headers and body flush as separate segments; Nagle would hold
        # the second until the gateway ACKs — per-request tens of ms
        disable_nagle_algorithm = True

        def log_message(self, *a):
            pass

        def _send(self, code: int, msg: str, data, status: int = 200,
                  extra: dict | None = None):
            payload = json.dumps(
                {"code": code, "msg": msg, "data": data}).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            # trace continuity, same as serve.py: echo the caller's
            # traceparent on every response
            tp = self.headers.get("traceparent")
            if tp:
                self.send_header("traceparent", tp)
            with st.lock:
                self.send_header("X-TDAPI-Slots", str(st.slots))
                self.send_header("X-TDAPI-Active", str(st.active))
                self.send_header("X-TDAPI-Queued", str(st.queued))
                # the serve.py KV-affinity advertisement: prefix sketch,
                # cached-prefix occupancy, and the smoothed queue wait
                self.send_header("X-TDAPI-KV-Sketch", st.sketch_hex)
                self.send_header("X-TDAPI-KV-Occ", str(len(st.prefixes)))
                if st.qwait_ewma is not None:
                    self.send_header("X-TDAPI-Queue-Wait-EWMA-Ms",
                                     str(round(st.qwait_ewma, 3)))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(payload)

        def do_GET(self):
            if self.path.startswith("/kv"):
                # prefill side of the disaggregated handoff: serve one
                # exported prompt-KV entry (single-take, TTL-purged)
                key = ""
                if "key=" in self.path:
                    key = self.path.split("key=", 1)[1].split("&", 1)[0]
                with st.lock:
                    st.purge_exports()
                    entry = st.kv_exports.pop(key, None)
                    if entry is not None:
                        st.kv_fetches += 1
                if entry is None:
                    self._send(404, "kv export not found", None,
                               status=404)
                    return
                self._send(200, "Success", {"tokens": entry["tokens"],
                                            "len": len(entry["tokens"]),
                                            "bufs": {}})
                return
            if self.path != "/healthz":
                self._send(404, "route not found", None)
                return
            with st.lock:
                st.purge_exports()
                batching = {
                    "slots": st.slots, "active": st.active,
                    "queued": st.queued, "alive": True,
                    "served": st.served, "shed": st.shed,
                    "queueWait": {"ewmaMs": st.qwait_ewma},
                    "prefixCache": {
                        "entries": len(st.prefixes),
                        "blocks": sum(len(k) for k in st.prefixes)
                        // max(kvaffinity.CHUNK_TOKENS, 1),
                        "hits": st.prefix_hits,
                        "kvExports": len(st.kv_exports),
                        "kvFetches": st.kv_fetches,
                        "handoffsIn": st.handoffs_in,
                        "sketch": st.sketch_hex,
                    },
                }
            self._send(200, "Success", {
                "model": model, "params": 0,
                "batching": batching,
            })

        def do_POST(self):
            if self.path != "/generate":
                self._send(404, "route not found", None)
                return
            try:
                length = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(length) or b"{}")
                tokens = body["tokens"]
                max_new = int(body.get("max_new", 16))
                if max_new < 1:
                    raise ValueError("max_new must be >= 1")
            except (KeyError, TypeError, ValueError) as e:
                self._send(400, f"bad request: {e}", None)
                return
            # replica-side fault gate, keyed by this replica's name: the
            # tail-tolerance e2e arms TDAPI_FAULTS="<gw>r0.generate:
            # jitter:0.05" in ONE replica's env to make exactly that
            # replica gray (slow or flaky but alive) while its fleet
            # peers stay healthy
            try:
                faults.fault_gate(
                    os.environ.get("TDAPI_REPLICA", "replica")
                    + ".generate")
            except faults.InjectedFault as e:
                self._send(500, f"injected replica fault: {e}", None)
                return
            # disaggregated handoff contract (serve.py's): Phase:prefill
            # runs one token and exports the prompt "KV" under the key;
            # KV-Source pulls a peer's export and skips that prefill
            hdr_key = self.headers.get("X-TDAPI-KV-Key") or ""
            kv_src = self.headers.get("X-TDAPI-KV-Source") or ""
            phase = self.headers.get("X-TDAPI-Phase") or ""
            kv_key = ""
            imported = 0
            row = list(tokens[0]) if (tokens and isinstance(tokens[0],
                                                            list)) else None
            if hdr_key and row is not None:
                if phase == "prefill":
                    kv_key, max_new = hdr_key, 1
                elif kv_src:
                    fetched = _fetch_kv(kv_src, hdr_key)
                    # STRICT prefix only: the last prompt position must
                    # run for real (the decode row carries one extra
                    # token past the exported prompt)
                    if (fetched and len(fetched) < len(row)
                            and row[:len(fetched)] == fetched):
                        imported = len(fetched)
                        with st.lock:
                            st.handoffs_in += 1
            # replica-side admission: shed past the queue bound so the
            # gateway re-routes instead of stacking waiters here
            with st.lock:
                if st.queued >= st.admit_queue:
                    st.shed += 1
                    do_shed = True
                else:
                    st.queued += 1
                    do_shed = False
            if do_shed:
                self._send(429, "replica queue full", None,
                           extra={"Retry-After": "1",
                                  "X-TDAPI-Shed": "1"})
                return
            t_enq = time.monotonic()
            st.slot_sem.acquire()
            # slot-wait telemetry, the serve.py contract: a fronting
            # worker stitches this into its forward span
            wait_ms = (time.monotonic() - t_enq) * 1e3
            with st.lock:
                st.queued -= 1
                st.active += 1
                prev = st.qwait_ewma
                st.qwait_ewma = (wait_ms if prev is None
                                 else 0.2 * wait_ms + 0.8 * prev)
            try:
                # the "prefill": per-prompt-token cost, discounted by
                # the longest warm prefix (stored prompt or handed-off
                # KV) — the time affinity routing and disaggregation
                # actually save over this mock
                if st.prefill_token_ms > 0 and row is not None:
                    with st.lock:
                        hit = max(st.prefix_hit(tuple(row)), imported)
                        if hit > 0:
                            st.prefix_hits += 1
                    time.sleep(
                        (len(row) - hit) * st.prefill_token_ms / 1e3)
                # the "decode": hold a slot for decode_ms per request
                time.sleep(st.decode_ms / 1e3)
                out = [list(r) + list(range(max_new)) for r in tokens]
                with st.lock:
                    if row is not None:
                        st.store_prefix(tuple(row))
                    if kv_key:
                        st.purge_exports()
                        st.kv_exports[kv_key] = {
                            "tokens": list(row),
                            "at": time.monotonic()}
            finally:
                with st.lock:
                    st.active -= 1
                    st.served += 1
                st.slot_sem.release()
            self._send(200, "Success", {"tokens": out},
                       extra={"X-TDAPI-Queue-Wait-Ms":
                              str(round(wait_ms, 3))})

    return Handler


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, default=0,
                   help="0 = $PORT from the process substrate, else 8000")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--slots", type=int, default=4,
                   help="concurrent in-flight requests (the batcher slots "
                        "the gateway admits against)")
    p.add_argument("--decode-ms", type=float, default=5.0,
                   help="per-request slot hold time (the simulated decode)")
    p.add_argument("--admit-queue", type=int, default=32,
                   help="replica-side queue bound; past it /generate sheds "
                        "429 so the gateway re-routes")
    p.add_argument("--init-ms", type=float, default=0.0,
                   help="one-time startup cost (simulated model load + "
                        "compile) — SKIPPED when the writable layer "
                        "already holds the warm marker (a CoW clone from "
                        "a warm donor)")
    p.add_argument("--warm-mb", type=int, default=0,
                   help="'weights' bytes written at init (what the clone "
                        "actually moves)")
    p.add_argument("--prefill-token-ms", type=float, default=0.0,
                   help="per-prompt-token prefill cost; discounted by the "
                        "longest warm prefix (stored prompt or handed-off "
                        "KV) — makes affinity routing measurable")
    p.add_argument("--kv-ttl", type=float, default=30.0,
                   help="seconds an un-fetched /kv export survives before "
                        "the purge frees it")
    args = p.parse_args(argv)
    port = args.port or int(os.environ.get("PORT", "8000"))

    warm = os.path.exists(READY_MARKER)
    if not warm:
        if args.init_ms > 0:
            time.sleep(args.init_ms / 1e3)
        if args.warm_mb > 0:
            with open("model.weights", "wb") as f:
                f.write(os.urandom(1024) * args.warm_mb * 1024)
        with open(READY_MARKER, "w") as f:
            f.write(json.dumps({"initMs": args.init_ms}))
    print(f"mock model {'WARM (cloned layer)' if warm else 'cold init'} — "
          f"{args.slots} slots, {args.decode_ms}ms decode", flush=True)

    st = _State(args.slots, args.decode_ms, args.admit_queue,
                prefill_token_ms=args.prefill_token_ms,
                kv_ttl=args.kv_ttl)
    httpd = ThreadingHTTPServer((args.host, port),
                                _handler_for(st, "mock"))
    print(f"mock model serving on {args.host}:{httpd.server_address[1]}",
          flush=True)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
