"""Mock model replica — the serving contract without the accelerator.

Speaks exactly the serving workload's HTTP surface (workloads/serve.py:
`GET /healthz` with the `batching` block, `POST /generate` with the
token-level envelope, the `X-TDAPI-*` admission headers), but the "model"
is a slot-bounded hold of --decode-ms per request instead of a jitted
decode loop. Exists so the GATEWAY control loop — routing, admission,
shedding, autoscale, clone-warm starts — can be exercised and priced
end-to-end over real processes and real HTTP without paying `import jax`
per replica (stdlib only: the warm pool absorbs the interpreter, and the
bench's router-overhead number prices the gateway, not the kernels).

Warm-start contract (the CoW-clone story, bench + e2e): startup costs
--init-ms once — simulating model load + first compile — then writes
--warm-mb of "weights" plus a `.model_ready` marker into the writable
layer. A replica whose layer was CLONED from a warm donor (gateway
scale-up) finds the marker and skips the init cost entirely: ready in
milliseconds, the same economics as a real replica inheriting its
donor's checkpoint/compile cache.
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

READY_MARKER = ".model_ready"


def launch_cmd(repo_root: str, *args: str) -> list:
    """Container cmd that launches this module from `repo_root` on any
    cwd (the process substrate chdirs into the container rootfs before
    exec, so a bare `-m` lookup would miss the repo). The `-c` form is
    warm-pool-eligible and needs no PYTHON* env (which would force a
    cold spawn — backend/warmpool.py supports())."""
    import sys
    code = (f"import sys; sys.path.insert(0, {repo_root!r}); "
            "from gpu_docker_api_tpu.workloads.mock_model import main; "
            f"raise SystemExit(main({list(args)!r}))")
    return [sys.executable, "-u", "-c", code]


class _State:
    def __init__(self, slots: int, decode_ms: float, admit_queue: int):
        self.slots = slots
        self.decode_ms = decode_ms
        self.admit_queue = admit_queue
        self.lock = threading.Lock()
        self.slot_sem = threading.Semaphore(slots)
        self.active = 0
        self.queued = 0
        self.served = 0
        self.shed = 0


def _handler_for(st: _State, model: str):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # headers and body flush as separate segments; Nagle would hold
        # the second until the gateway ACKs — per-request tens of ms
        disable_nagle_algorithm = True

        def log_message(self, *a):
            pass

        def _send(self, code: int, msg: str, data, status: int = 200,
                  extra: dict | None = None):
            payload = json.dumps(
                {"code": code, "msg": msg, "data": data}).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            # trace continuity, same as serve.py: echo the caller's
            # traceparent on every response
            tp = self.headers.get("traceparent")
            if tp:
                self.send_header("traceparent", tp)
            with st.lock:
                self.send_header("X-TDAPI-Slots", str(st.slots))
                self.send_header("X-TDAPI-Active", str(st.active))
                self.send_header("X-TDAPI-Queued", str(st.queued))
            for k, v in (extra or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(payload)

        def do_GET(self):
            if self.path != "/healthz":
                self._send(404, "route not found", None)
                return
            with st.lock:
                batching = {
                    "slots": st.slots, "active": st.active,
                    "queued": st.queued, "alive": True,
                    "served": st.served, "shed": st.shed,
                }
            self._send(200, "Success", {
                "model": model, "params": 0,
                "batching": batching,
            })

        def do_POST(self):
            if self.path != "/generate":
                self._send(404, "route not found", None)
                return
            try:
                length = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(length) or b"{}")
                tokens = body["tokens"]
                max_new = int(body.get("max_new", 16))
                if max_new < 1:
                    raise ValueError("max_new must be >= 1")
            except (KeyError, TypeError, ValueError) as e:
                self._send(400, f"bad request: {e}", None)
                return
            # replica-side admission: shed past the queue bound so the
            # gateway re-routes instead of stacking waiters here
            with st.lock:
                if st.queued >= st.admit_queue:
                    st.shed += 1
                    do_shed = True
                else:
                    st.queued += 1
                    do_shed = False
            if do_shed:
                self._send(429, "replica queue full", None,
                           extra={"Retry-After": "1",
                                  "X-TDAPI-Shed": "1"})
                return
            t_enq = time.monotonic()
            st.slot_sem.acquire()
            # slot-wait telemetry, the serve.py contract: a fronting
            # worker stitches this into its forward span
            wait_ms = (time.monotonic() - t_enq) * 1e3
            with st.lock:
                st.queued -= 1
                st.active += 1
            try:
                # the "decode": hold a slot for decode_ms * ceil(tokens)
                time.sleep(st.decode_ms / 1e3)
                out = [list(row) + list(range(max_new)) for row in tokens]
            finally:
                with st.lock:
                    st.active -= 1
                    st.served += 1
                st.slot_sem.release()
            self._send(200, "Success", {"tokens": out},
                       extra={"X-TDAPI-Queue-Wait-Ms":
                              str(round(wait_ms, 3))})

    return Handler


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, default=0,
                   help="0 = $PORT from the process substrate, else 8000")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--slots", type=int, default=4,
                   help="concurrent in-flight requests (the batcher slots "
                        "the gateway admits against)")
    p.add_argument("--decode-ms", type=float, default=5.0,
                   help="per-request slot hold time (the simulated decode)")
    p.add_argument("--admit-queue", type=int, default=32,
                   help="replica-side queue bound; past it /generate sheds "
                        "429 so the gateway re-routes")
    p.add_argument("--init-ms", type=float, default=0.0,
                   help="one-time startup cost (simulated model load + "
                        "compile) — SKIPPED when the writable layer "
                        "already holds the warm marker (a CoW clone from "
                        "a warm donor)")
    p.add_argument("--warm-mb", type=int, default=0,
                   help="'weights' bytes written at init (what the clone "
                        "actually moves)")
    args = p.parse_args(argv)
    port = args.port or int(os.environ.get("PORT", "8000"))

    warm = os.path.exists(READY_MARKER)
    if not warm:
        if args.init_ms > 0:
            time.sleep(args.init_ms / 1e3)
        if args.warm_mb > 0:
            with open("model.weights", "wb") as f:
                f.write(os.urandom(1024) * args.warm_mb * 1024)
        with open(READY_MARKER, "w") as f:
            f.write(json.dumps({"initMs": args.init_ms}))
    print(f"mock model {'WARM (cloned layer)' if warm else 'cold init'} — "
          f"{args.slots} slots, {args.decode_ms}ms decode", flush=True)

    st = _State(args.slots, args.decode_ms, args.admit_queue)
    httpd = ThreadingHTTPServer((args.host, port),
                                _handler_for(st, "mock"))
    print(f"mock model serving on {args.host}:{httpd.server_address[1]}",
          flush=True)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
