"""Name → latest-version maps and the merged-layer map.

Reference parity: internal/version/version.go (ContainerVersionMap /
VolumeVersionMap :11-14, etcd load at boot :28-41/:94-109, async persist on
every Set/Remove :59-92, flush at Stop :43-51) and internal/version/merge.go
(version→mergedLayerPath, persisted only at Close :28-33).

Fixes over the reference:
- the maps are mutex-protected (the reference's are bare Go maps mutated from
  request goroutines — a latent data race, SURVEY §5.2);
- each map persists only itself (the reference persists BOTH maps on any
  change of either, version.go:81-92 — SURVEY §2 bug 6);
- bump() is atomic get+increment, so two concurrent runs can't mint the same
  version.
"""

from __future__ import annotations

import json
import re
import threading
from typing import Optional

from .store.client import StateClient
from .workqueue import PutKeyValue, WorkQueue

CONTAINER_VERSION_MAP_KEY = "containerVersionMap"
VOLUME_VERSION_MAP_KEY = "volumeVersionMap"
MERGE_MAP_KEY = "containerMergeMap"
_MAPS_RESOURCE = "maps"


class VersionMap:
    def __init__(self, map_key: str, client: StateClient, wq: Optional[WorkQueue] = None):
        self._key = map_key
        self._client = client
        self._wq = wq
        self._lock = threading.Lock()
        self._m: dict[str, int] = {}
        self._load()

    def _load(self) -> None:
        kv = self._client.get(_MAPS_RESOURCE, self._key)
        if kv is not None:
            try:
                self._m = {k: int(v) for k, v in json.loads(kv.value).items()}
            except (json.JSONDecodeError, ValueError, AttributeError):
                self._m = {}

    # ---- reference API shape: Set/Get/Exist/Remove ----

    def get(self, name: str) -> Optional[int]:
        with self._lock:
            return self._m.get(name)

    def exist(self, name: str) -> bool:
        with self._lock:
            return name in self._m

    # Persisting while still holding the lock keeps snapshot order == persist
    # order; submitting outside it would let an older snapshot land last.

    def set(self, name: str, version: int) -> None:
        with self._lock:
            self._m[name] = version
            self._persist(dict(self._m))

    def bump(self, name: str) -> int:
        """Atomically assign the next version (first version is 1)."""
        with self._lock:
            v = self._m.get(name, 0) + 1
            self._m[name] = v
            self._persist(dict(self._m))
            return v

    def rollback_bump(self, name: str, to_version: int) -> None:
        """Undo a failed bump (reference defer at replicaset_nomock.go:45-55)."""
        with self._lock:
            if to_version <= 0:
                self._m.pop(name, None)
            else:
                self._m[name] = to_version
            self._persist(dict(self._m))

    def remove(self, name: str) -> None:
        with self._lock:
            self._m.pop(name, None)
            self._persist(dict(self._m))

    def items(self) -> dict[str, int]:
        with self._lock:
            return dict(self._m)

    # ---- persistence ----

    def _persist(self, snapshot: dict[str, int]) -> None:
        payload = json.dumps(snapshot, sort_keys=True)
        if self._wq is not None:
            self._wq.submit(PutKeyValue(_MAPS_RESOURCE, self._key, payload))
        else:
            self._client.put(_MAPS_RESOURCE, self._key, payload)

    # tdlint: disable=io-under-lock -- deliberate: shutdown flush writes
    # under the lock so a concurrent mutation's persist can't be overwritten
    def flush(self) -> None:
        with self._lock:
            self._client.put(_MAPS_RESOURCE, self._key,
                             json.dumps(self._m, sort_keys=True))


class MergeMap:
    """container-version-name → merged-layer (upper-dir snapshot) path.

    Reference: internal/version/merge.go. Persisted on every mutation here
    (the reference persists only at Close — a crash loses it)."""

    def __init__(self, client: StateClient, wq: Optional[WorkQueue] = None):
        self._client = client
        self._wq = wq
        self._lock = threading.Lock()
        self._m: dict[str, str] = {}
        kv = self._client.get(_MAPS_RESOURCE, MERGE_MAP_KEY)
        if kv is not None:
            try:
                self._m = dict(json.loads(kv.value))
            except json.JSONDecodeError:
                self._m = {}

    def get(self, container_name: str) -> Optional[str]:
        with self._lock:
            return self._m.get(container_name)

    def set(self, container_name: str, path: str) -> None:
        with self._lock:
            self._m[container_name] = path
            self._persist(dict(self._m))

    def remove_replicaset(self, replicaset_name: str) -> list[str]:
        """Drop all entries for versions of one replicaSet; returns removed
        paths (reference deletes the whole merges/{rs} dir on container
        delete, replicaset.go:706-715). Matches `{name}-{digits}` exactly —
        replicaSet names may not contain dashes, but don't rely on that."""
        pat = re.compile(re.escape(replicaset_name) + r"-\d+$")
        with self._lock:
            gone = [p for n, p in self._m.items() if pat.fullmatch(n)]
            self._m = {n: p for n, p in self._m.items() if not pat.fullmatch(n)}
            self._persist(dict(self._m))
        return gone

    def items(self) -> dict[str, str]:
        with self._lock:
            return dict(self._m)

    def _persist(self, snapshot: dict[str, str]) -> None:
        payload = json.dumps(snapshot, sort_keys=True)
        if self._wq is not None:
            self._wq.submit(PutKeyValue(_MAPS_RESOURCE, MERGE_MAP_KEY, payload))
        else:
            self._client.put(_MAPS_RESOURCE, MERGE_MAP_KEY, payload)

    # tdlint: disable=io-under-lock -- deliberate: shutdown flush writes
    # under the lock so a concurrent mutation's persist can't be overwritten
    def flush(self) -> None:
        with self._lock:
            self._client.put(_MAPS_RESOURCE, MERGE_MAP_KEY,
                             json.dumps(self._m, sort_keys=True))
